"""Discrete-event simulator of the mixed scheduler + router.

A host-only model of the serving stack for policy search at scales
the sandbox cannot run live (100k+ concurrent sessions simulate in
seconds): token-budget iterations, chunked prefill, weighted (DRR-
style) admission across QoS classes, page-pool preemption, a radix
prefix-cache model for shared system prompts, and the router's
least-loaded + tenant-affinity placement.

Calibration: iteration wall time is NOT modeled from first
principles — ``CostModel.fit`` regresses it from the flight
recorder's measured per-iteration records (``duration_ms`` vs
``tokens_scheduled``), so the sim inherits the live stack's real
per-token and fixed costs. tests/test_scenarios.py asserts sim-vs-
live agreement on a small shared scenario (the tolerance is
documented in docs/scenarios.md).

Latency accounting mirrors serving_metrics: ``queue_wait`` =
submit -> admission, ``ttft`` = submit -> first emitted token,
``itl`` = gap between consecutive emitted tokens, ``e2e`` =
submit -> finish. Observations feed a real ``slo.SLOTracker`` on a
VIRTUAL clock, so sim attainment/burn reports are directly
comparable with a live ``slo_report()``.

Pure host-side policy: stdlib only — no jax, no numpy (DD3 roster).
"""

from __future__ import annotations

import heapq
import zlib
from dataclasses import dataclass

from cloud_server_tpu.inference.slo import SLOTracker


@dataclass
class CostModel:
    """Per-iteration wall cost: ``fixed_ms + per_token_ms * tokens``.

    Fit from flight-recorder records so simulated time reflects the
    measured stack, not a guess."""

    fixed_ms: float = 2.0
    per_token_ms: float = 0.05

    def iteration_ms(self, tokens: int) -> float:
        return self.fixed_ms + self.per_token_ms * max(0, tokens)

    @classmethod
    def fit(cls, records, *, default: "CostModel | None" = None
            ) -> "CostModel":
        """Least-squares fit of ``duration_ms`` against
        ``tokens_scheduled`` over busy flight records. Falls back to
        ``default`` when the window has no spread to regress on."""
        pts = [(float(r["tokens_scheduled"]), float(r["duration_ms"]))
               for r in records
               if r.get("tokens_scheduled", 0) > 0
               and r.get("duration_ms") is not None]
        base = default or cls()
        if len(pts) < 2:
            return base
        n = len(pts)
        mx = sum(x for x, _ in pts) / n
        my = sum(y for _, y in pts) / n
        var = sum((x - mx) ** 2 for x, _ in pts)
        if var <= 1e-9:
            # no spread: keep the measured mean as the fixed cost
            return cls(fixed_ms=max(0.0, my), per_token_ms=0.0)
        slope = sum((x - mx) * (y - my) for x, y in pts) / var
        slope = max(0.0, slope)
        fixed = max(0.0, my - slope * mx)
        return cls(fixed_ms=fixed, per_token_ms=slope)


class _SimReq:
    __slots__ = ("event", "cls", "arrival", "admit_t", "prefill_left",
                 "decoded", "first_tok_t", "last_tok_t", "itl_s",
                 "preempted")

    def __init__(self, event, cls: str, arrival: float):
        self.event = event
        self.cls = cls
        self.arrival = arrival
        self.admit_t: float | None = None
        self.prefill_left = len(event.prompt)
        self.decoded = 0
        self.first_tok_t: float | None = None
        self.last_tok_t: float | None = None
        self.itl_s: list[float] = []
        self.preempted = 0

    def pages_needed(self, page_size: int) -> int:
        ctx = len(self.event.prompt) + self.decoded
        return -(-max(1, ctx) // page_size)


class SimReplica:
    """One simulated mixed-scheduler server. Each ``step()`` is one
    scheduler iteration: every decoding slot emits one token, the
    leftover token budget prefills admitted-but-incomplete requests
    in ``prefill_chunk`` quanta, and free slots admit pending work in
    weighted class order (the DRR shape of qos.py's admission)."""

    def __init__(self, *, max_slots: int = 8, budget: int = 256,
                 chunk: int = 64, page_size: int = 16,
                 pages: int | None = None,
                 class_weights: dict[str, float] | None = None):
        self.max_slots = int(max_slots)
        self.budget = int(budget)
        self.chunk = int(chunk)
        self.page_size = int(page_size)
        self.pages = pages  # None = unbounded pool (no preemption)
        self.class_weights = dict(class_weights or {})
        self.t = 0.0                      # this replica's clock
        self.active: list[_SimReq] = []   # admission order
        self.pending: dict[str, list[_SimReq]] = {}
        self._credit: dict[str, float] = {}
        self._seen_prefixes: set = set()  # radix prefix-cache model
        self.preemptions = 0
        self.iterations = 0

    # -- load view (the router model's placement inputs) ----------------

    @property
    def num_active(self) -> int:
        return len(self.active)

    @property
    def num_pending(self) -> int:
        return sum(len(q) for q in self.pending.values())

    @property
    def busy(self) -> bool:
        return bool(self.active or self.num_pending)

    def submit(self, req: _SimReq, now: float) -> None:
        self.t = max(self.t, now)
        self.pending.setdefault(req.cls, []).append(req)

    def _pages_in_use(self) -> int:
        return sum(r.pages_needed(self.page_size) for r in self.active)

    def _admit_order(self) -> list[str]:
        """Weighted class order: classes spend credit proportional to
        their weight before the round resets — heavier classes admit
        first and more often, the DRR admission shape."""
        cands = [c for c, q in self.pending.items() if q]
        if not cands:
            return []
        if all(self._credit.get(c, 0.0) <= 0.0 for c in cands):
            for c in cands:
                self._credit[c] = self.class_weights.get(c, 1.0)
        return sorted(cands, key=lambda c: -self._credit.get(c, 0.0))

    def _admit(self, now: float) -> None:
        while len(self.active) < self.max_slots:
            order = self._admit_order()
            if not order:
                return
            cls = order[0]
            req = self.pending[cls].pop(0)
            self._credit[cls] = self._credit.get(cls, 1.0) - 1.0
            req.admit_t = now if req.admit_t is None else req.admit_t
            e = req.event
            if e.prefix_len > 0:
                key = (e.tenant, e.prefix_len)
                if key in self._seen_prefixes:
                    # shared system prefix already resident: the radix
                    # cache skips recomputing it
                    req.prefill_left = min(
                        req.prefill_left, len(e.prompt) - e.prefix_len)
                else:
                    self._seen_prefixes.add(key)
            self.active.append(req)

    def step(self, cost: CostModel) -> tuple[float, list[_SimReq]]:
        """One iteration. Returns (duration_s, finished requests);
        advances this replica's clock to the iteration end."""
        start = self.t
        self._admit(start)
        decoders = [r for r in self.active if r.prefill_left == 0]
        tokens = len(decoders)
        budget_left = max(0, self.budget - tokens)
        # chunked prefill in admission order within the leftover budget
        for r in self.active:
            if budget_left <= 0:
                break
            if r.prefill_left > 0:
                take = min(self.chunk, r.prefill_left, budget_left)
                r.prefill_left -= take
                tokens += take
                budget_left -= take
        # page-pool pressure: preempt the youngest admission when the
        # pool cannot hold every active context (the live scheduler's
        # _preempt_youngest; the victim re-queues and re-prefills)
        if self.pages is not None:
            # a lone oversized context is allowed to run over the pool
            # (the live server fails it at submit; the sim just serves
            # it) — preemption ping-pong must terminate
            while len(self.active) > 1 and (self._pages_in_use()
                                            > self.pages):
                victim = self.active.pop()
                victim.prefill_left = len(victim.event.prompt)
                victim.decoded = 0
                victim.preempted += 1
                self.preemptions += 1
                self.pending.setdefault(victim.cls, []).insert(0, victim)
        dt = cost.iteration_ms(tokens) / 1e3
        end = start + dt
        finished: list[_SimReq] = []
        for r in decoders:
            if r not in self.active:
                continue  # preempted this iteration
            r.decoded += 1
            if r.first_tok_t is None:
                r.first_tok_t = end
            else:
                r.itl_s.append(end - r.last_tok_t)
            r.last_tok_t = end
            if r.decoded >= r.event.max_new_tokens:
                finished.append(r)
        for r in finished:
            self.active.remove(r)
        self.t = end
        self.iterations += 1
        return dt, finished


def _pct(xs: list[float], p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(p * len(xs)))]


class FleetSim:
    """Runs one scenario event stream over a simulated fleet.

    Placement mirrors ``ReplicatedRouter._pick``: least
    (active + pending) load, ties broken round-robin from the
    tenant's crc32 home offset (affinity concentrates a tenant's
    shared prefix on one replica, exactly like the live router).
    Session turns follow the replay driver's rule: turn k fires
    ``think_s`` after turn k-1 completes."""

    def __init__(self, replicas: list[SimReplica], *,
                 cost: CostModel | None = None,
                 slo: dict | None = None,
                 tenant_class: dict[str, str] | None = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = replicas
        self.cost = cost or CostModel()
        self.tenant_class = dict(tenant_class or {})
        self.now = 0.0
        self.tracker = (SLOTracker(slo, clock=lambda: self.now)
                        if slo else None)
        self.finished: list[_SimReq] = []
        self.peak_active = 0

    def _cls(self, tenant: str | None) -> str:
        return self.tenant_class.get(tenant, "default")

    def _place(self, tenant: str | None) -> SimReplica:
        n = len(self.replicas)
        k = (zlib.crc32(tenant.encode()) % n
             if tenant is not None else 0)
        loads = [r.num_active + r.num_pending for r in self.replicas]
        i = min(range(n), key=lambda j: (loads[j], (j - k) % n))
        return self.replicas[i]

    def _observe(self, req: _SimReq, done_t: float) -> None:
        if self.tracker is None:
            return
        obs = self.tracker.observe
        cls = req.cls
        obs(cls, "queue_wait", req.admit_t - req.arrival, done_t)
        obs(cls, "ttft", req.first_tok_t - req.arrival, done_t)
        for gap in req.itl_s:
            obs(cls, "itl", gap, done_t)
        obs(cls, "e2e", done_t - req.arrival, done_t)

    def run(self, events, *, max_sim_s: float = 1e6) -> dict:
        # (due_time, seq, event) heap; turn-k events enter when turn
        # k-1 completes, at completion + think_s
        heap: list = []
        seq = 0
        sessions: dict[int, list] = {}
        for e in sorted(events, key=lambda e: (e.time_s, e.turn)):
            sessions.setdefault(e.session, []).append(e)
        for sid, evs in sessions.items():
            heapq.heappush(heap, (evs[0].time_s, seq, evs[0]))
            seq += 1
            sessions[sid] = evs[1:]
        while heap or any(r.busy for r in self.replicas):
            busy = [r for r in self.replicas if r.busy]
            next_due = heap[0][0] if heap else None
            if busy:
                r = min(busy, key=lambda r: r.t)
                if next_due is not None and next_due <= r.t:
                    _, _, e = heapq.heappop(heap)
                    self.now = max(self.now, next_due)
                    req = _SimReq(e, self._cls(e.tenant), next_due)
                    self._place(e.tenant).submit(req, next_due)
                    continue
                _, finished = r.step(self.cost)
                self.now = max(self.now, r.t)
                self.peak_active = max(
                    self.peak_active,
                    sum(x.num_active for x in self.replicas))
                for req in finished:
                    self.finished.append(req)
                    self._observe(req, r.t)
                    rest = sessions.get(req.event.session)
                    if rest:
                        nxt = rest.pop(0)
                        heapq.heappush(
                            heap, (r.t + nxt.think_s, seq, nxt))
                        seq += 1
            else:
                if next_due is None:
                    break
                _, _, e = heapq.heappop(heap)
                self.now = max(self.now, next_due)
                req = _SimReq(e, self._cls(e.tenant), next_due)
                self._place(e.tenant).submit(req, next_due)
            if self.now > max_sim_s:
                raise RuntimeError(
                    f"simulation exceeded max_sim_s={max_sim_s}")
        return self.report()

    def report(self) -> dict:
        per_class: dict[str, dict] = {}
        for req in self.finished:
            c = per_class.setdefault(
                req.cls, {"count": 0, "ttft_s": [], "itl_s": [],
                          "e2e_s": [], "queue_wait_s": []})
            c["count"] += 1
            c["ttft_s"].append(req.first_tok_t - req.arrival)
            c["itl_s"] += req.itl_s
            c["e2e_s"].append(req.last_tok_t - req.arrival)
            c["queue_wait_s"].append(req.admit_t - req.arrival)
        out_classes = {}
        for cls, c in per_class.items():
            out_classes[cls] = {
                "count": c["count"],
                "ttft_p50_s": _pct(c["ttft_s"], 0.50),
                "ttft_p95_s": _pct(c["ttft_s"], 0.95),
                "itl_p50_s": _pct(c["itl_s"], 0.50),
                "itl_p95_s": _pct(c["itl_s"], 0.95),
                "e2e_p50_s": _pct(c["e2e_s"], 0.50),
                "queue_wait_p50_s": _pct(c["queue_wait_s"], 0.50)}
        return {
            "finished": len(self.finished),
            "sim_duration_s": self.now,
            "iterations": sum(r.iterations for r in self.replicas),
            "preemptions": sum(r.preemptions for r in self.replicas),
            "peak_active": self.peak_active,
            "classes": out_classes,
            "slo": (self.tracker.report(self.now)
                    if self.tracker is not None else None)}

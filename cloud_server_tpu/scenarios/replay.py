"""Replay driver: fires a scenario event stream at a live target.

The target is anything with ``submit(prompt, **kw)`` — a
``PagedInferenceServer``, a ``ReplicatedRouter`` — or an ``HttpTarget``
wrapping the HTTP frontend, so the same stream can drive one replica,
a fleet, or the full wire path.

Timing contract (shared with the simulator so both consume a stream
identically): a turn-0 event fires when the scenario clock reaches its
``time_s``; a turn-k event fires ``think_s`` after turn k-1 actually
completed. ``tick(now)`` is the non-blocking serving-path entry point
(registered on the hot-path lint roster — it runs interleaved with
scheduler steps); ``run()`` is the wall-clock convenience loop around
it.

A replay never *loses* requests silently: every fired handle is kept,
``result()`` classifies completed vs failed (error finish reasons) vs
rejected (backpressure refusals at submit), and the scenario-harness
metric families (``cloud_server_scenario_*``) are registered eagerly
for the docs drift check.

Pure host-side policy: stdlib only — no jax, no numpy (DD3 roster).
"""

from __future__ import annotations

import threading
import time
import urllib.request

from cloud_server_tpu.utils.serving_metrics import MetricsRegistry


class _HttpHandle:
    """Request-handle shim over one non-streaming HTTP completion:
    exposes the ``done`` / ``finish_reason`` surface the driver's
    bookkeeping reads on real Request handles."""

    def __init__(self):
        self._done = threading.Event()
        self.finish_reason: str = ""
        self.text: str = ""

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)


class HttpTarget:
    """Fires events against the HTTP frontend (``/v1/completions``,
    non-streaming; tenant identity rides the X-Tenant header exactly
    as documented in http_server.py). Each submit runs on its own
    daemon thread so the driver's tick loop never blocks on the
    wire."""

    def __init__(self, base_url: str, *, timeout_s: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def submit(self, prompt, *, max_new_tokens: int | None = None,
               tenant: str | None = None, **kw) -> _HttpHandle:
        import json as _json
        h = _HttpHandle()
        body = {"prompt": list(prompt), "stream": False}
        if max_new_tokens is not None:
            body["max_tokens"] = int(max_new_tokens)
        headers = {"Content-Type": "application/json"}
        if tenant is not None:
            headers["X-Tenant"] = tenant
        req = urllib.request.Request(
            self.base_url + "/v1/completions",
            data=_json.dumps(body).encode(), headers=headers)

        def worker():
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    out = _json.loads(resp.read())
                choice = (out.get("choices") or [{}])[0]
                h.finish_reason = choice.get("finish_reason", "stop")
                h.text = choice.get("text", "")
            except Exception as exc:  # noqa: BLE001 — recorded, surfaced
                h.finish_reason = f"error: {exc!r}"[:160]
            finally:
                h._done.set()

        threading.Thread(target=worker, daemon=True,
                         name="scenario-http").start()
        return h


class _Session:
    __slots__ = ("events", "prev", "prev_done_at")

    def __init__(self):
        self.events = []          # reversed: pop() yields next turn
        self.prev = None          # previous turn's live handle
        self.prev_done_at = None  # scenario time its completion was seen


class ReplayDriver:
    """Drives one event stream against one target.

    ``tick(now)`` fires every event that is due at scenario time
    ``now`` and returns how many fired; it never sleeps, logs, or
    reads a clock (the caller owns time — a test passes virtual time,
    ``run()`` passes scaled wall time), so it can interleave with
    synchronous ``step()`` pumping."""

    def __init__(self, target, events, *, submit_kw: dict | None = None,
                 registry: MetricsRegistry | None = None):
        self.target = target
        self.submit_kw = dict(submit_kw or {})
        self._sessions: dict[int, _Session] = {}
        for e in sorted(events, key=lambda e: (e.time_s, e.turn),
                        reverse=True):
            self._sessions.setdefault(e.session, _Session()).events \
                .append(e)
        self.handles: list[tuple[object, object]] = []  # (event, handle)
        self.rejected: list[tuple[object, str]] = []
        # scenario-harness metric families — registered EAGERLY so
        # they exist for the docs drift check before any event fires
        reg = self._registry = registry or MetricsRegistry()
        self._m_fired = reg.counter(
            "scenario_events_fired_total",
            "Scenario events submitted to the replay target")
        self._m_rejected = reg.counter(
            "scenario_events_rejected_total",
            "Scenario events refused at submit (backpressure/429 "
            "class) — counted, never retried by the driver")
        self._m_sessions = reg.counter(
            "scenario_sessions_total",
            "Distinct sessions in the replayed event stream")
        self._m_sessions.set_total(len(self._sessions))
        self._lag_ms = reg.histogram(
            "scenario_replay_lag_ms",
            "Firing lag behind the scenario schedule (tick time minus "
            "nominal due time), ms",
            buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                     1000.0, 2500.0, 5000.0))

    # -- serving path (hot-path roster) ---------------------------------

    def tick(self, now: float) -> int:
        """Fire everything due at scenario time ``now``."""
        fired = 0
        for sess in self._sessions.values():
            while sess.events:
                e = sess.events[-1]
                if e.turn > 0:
                    prev = sess.prev
                    if prev is None or not prev.done:
                        break
                    if sess.prev_done_at is None:
                        sess.prev_done_at = now
                    due = sess.prev_done_at + e.think_s
                else:
                    due = e.time_s
                if now < due:
                    break
                sess.events.pop()
                sess.prev_done_at = None
                sess.prev = self._fire(e, now - due)
                fired += 1
        return fired

    def _fire(self, e, lag_s: float):
        kw = dict(self.submit_kw)
        kw["max_new_tokens"] = e.max_new_tokens
        if e.tenant is not None:
            kw["tenant"] = e.tenant
        try:
            h = self.target.submit(list(e.prompt), **kw)
        except Exception as exc:  # noqa: BLE001 — refusal, not a loss
            self._m_rejected.inc()
            self.rejected.append((e, repr(exc)[:160]))
            return None
        self._m_fired.inc()
        self._lag_ms.observe(max(0.0, lag_s) * 1e3)
        self.handles.append((e, h))
        return h

    # -- read path -------------------------------------------------------

    @property
    def exhausted(self) -> bool:
        """Every event fired (or rejected)."""
        return all(not s.events for s in self._sessions.values())

    @property
    def done(self) -> bool:
        return self.exhausted and all(h.done for _, h in self.handles)

    def run(self, *, speed: float = 1.0, poll_s: float = 0.002,
            step=None, timeout_s: float | None = None) -> dict:
        """Wall-clock replay: scenario time advances at ``speed``x
        real time. With ``step`` (a callable) the target is pumped
        synchronously between ticks; without it the target is assumed
        to run its own scheduler threads."""
        t0 = time.monotonic()
        deadline = None if timeout_s is None else t0 + timeout_s
        while not self.done:
            now = time.monotonic()
            if deadline is not None and now > deadline:
                break
            self.tick((now - t0) * speed)
            if step is not None:
                step()
            else:
                time.sleep(poll_s)
        return self.result()

    def result(self) -> dict:
        failed = [(e, h.finish_reason) for e, h in self.handles
                  if h.done and str(getattr(h, "finish_reason", "")
                                    or "").startswith("error")]
        completed = (sum(1 for _, h in self.handles if h.done)
                     - len(failed))
        return {"fired": len(self.handles),
                "completed": completed,
                "failed": len(failed),
                "failures": [(e.session, e.turn, r)
                             for e, r in failed][:16],
                "rejected": len(self.rejected),
                "outstanding": sum(1 for _, h in self.handles
                                   if not h.done)}

    def metrics_snapshot(self) -> dict:
        return self._registry.snapshot()

"""Scenario harness: trace-driven workloads for an elastic fleet.

Three layers (docs/scenarios.md):

  * ``workload``   — seeded, composable generators (arrival processes,
    length mixtures, multi-turn sessions with shared system prefixes,
    tenant mixes) emitting a DETERMINISTIC event stream.
  * ``replay``     — fires an event stream against a live target:
    ``ReplicatedRouter.submit()`` / ``PagedInferenceServer.submit()``
    directly, or the HTTP frontend over the wire.
  * ``simulator``  — a host-only discrete-event model of the mixed
    scheduler + router, calibrated from flight-recorder iteration
    costs, for policy search at scales the sandbox cannot run live.
  * ``autoscaler`` — the SLO-burn-rate policy loop that closes the
    loop from ``ReplicatedRouter.slo_report()`` burn rates to the
    runtime fleet-mutation APIs (``add_replica``/``remove_replica``).

Nothing in this package is imported by the serving path; an
unconfigured deployment is byte-identical with or without it (pinned
by the scenario dispatch-count guard clone in
tests/test_scenarios.py).
"""

from cloud_server_tpu.scenarios.workload import (  # noqa: F401
    Event, LengthMixture, MMPPArrivals, PoissonArrivals, Scenario,
    SessionShape, TenantMix, TraceArrivals, diurnal_burst,
    stream_bytes)
from cloud_server_tpu.scenarios.replay import (  # noqa: F401
    HttpTarget, ReplayDriver)
from cloud_server_tpu.scenarios.simulator import (  # noqa: F401
    CostModel, FleetSim, SimReplica)
from cloud_server_tpu.scenarios.autoscaler import (  # noqa: F401
    AutoscalerConfig, SLOBurnAutoscaler)

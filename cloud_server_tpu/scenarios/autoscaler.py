"""SLO-burn-rate autoscaler: the policy loop over ReplicatedRouter.

Closes the loop the SLO engine left open: ``slo_report()`` already
computes SRE-workbook multi-window burn rates per priority class, and
the router already has runtime fleet mutation
(``add_replica``/``remove_replica``). This module is ONLY the policy
in between:

  * Scale UP when any watched (class, metric) pair burns its error
    budget on BOTH the fast and the slow window (the multi-window
    rule: fast-only is noise, slow-only is already lost) — or when
    pending depth per replica crosses the queue backstop (works with
    no SLO config at all).
  * Scale DOWN only when every watched pair is comfortably under
    budget on both windows AND the queue is near-empty; the victim
    is evacuated with ``remove_replica(migrate=True)`` — scale-down
    loses zero requests (regression-tested).
  * Hysteresis/cooldown: at most one action per ``hold_s`` window
    (anomaly.py's hold_s idiom), so a burst edge cannot flap the
    fleet.
  * Role awareness (disaggregated fleets): ttft/queue_wait burns add
    prefill capacity, itl burns add decode capacity; anything else —
    or a colocated fleet — adds colocated replicas.

The ``cloud_server_autoscaler_*`` metric families are registered
EAGERLY into the router's registry at construction (docs drift
check), so they exist whether or not a scale event ever fires. An
unconfigured deployment never constructs this class — zero added
work (the scenario dispatch-count guard clone pins this).

Replica lifecycle is delegated: ``spawn(role) -> replica | None``
supplies capacity (a warm pool, a fresh construction, a remote
allocation); ``release(replica)`` takes removed replicas (default:
``replica.stop()``). The autoscaler never builds servers itself —
that keeps it jax-free (DD3 roster).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import logging

_log = logging.getLogger(__name__)

_ROLE_PREFILL_METRICS = ("ttft", "queue_wait")
_ROLE_DECODE_METRICS = ("itl",)


@dataclass
class AutoscalerConfig:
    """Knobs (docs/scenarios.md catalogs them). Burn thresholds are
    in error-budget-burn units: 1.0 = the budget exhausts exactly at
    the objective horizon."""

    min_replicas: int = 1
    max_replicas: int = 4
    classes: tuple = ("interactive", "default")
    metrics: tuple = ("ttft", "e2e", "itl", "queue_wait")
    up_fast_burn: float = 2.0
    up_slow_burn: float = 1.0
    down_fast_burn: float = 0.5
    down_slow_burn: float = 0.5
    pending_high: float = 8.0
    pending_low: float = 1.0
    hold_s: float = 10.0
    poll_s: float = 1.0
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                "need 1 <= min_replicas <= max_replicas "
                f"(got {self.min_replicas}..{self.max_replicas})")
        if self.hold_s < 0 or self.poll_s <= 0:
            raise ValueError("hold_s must be >= 0, poll_s > 0")


@dataclass
class ScaleEvent:
    t: float
    action: str
    role: str
    replicas: int
    reason: str = ""

    def to_json(self) -> dict:
        return {"t": round(self.t, 3), "action": self.action,
                "role": self.role, "replicas": self.replicas,
                "reason": self.reason}


class SLOBurnAutoscaler:
    """One policy loop per router. Drive it with ``step()`` from your
    own loop (benches, tests) or ``start()`` a daemon polling at
    ``poll_s``."""

    def __init__(self, router, spawn, *, release=None,
                 config: AutoscalerConfig | None = None,
                 clock=time.monotonic):
        self.router = router
        self.spawn = spawn
        self.release = release if release is not None else (
            lambda r: r.stop())
        self.cfg = config or AutoscalerConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._last_action_at: float | None = None
        self.events: list[ScaleEvent] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # eager registration into the ROUTER's registry: the families
        # ride metrics_snapshot()/ /metrics with the rest of the fleet
        # plumbing, and exist before any scale event (docs drift check)
        reg = router._registry
        self._m_up = reg.counter(
            "autoscaler_scale_up_total",
            "Replicas added by the SLO-burn autoscaler")
        self._m_down = reg.counter(
            "autoscaler_scale_down_total",
            "Replicas drained (migrate=True) and removed by the "
            "autoscaler")
        self._m_blocked = reg.counter(
            "autoscaler_scale_blocked_total",
            "Scale decisions that could not act (spawn pool empty, "
            "min/max clamp, drain timeout)")
        self._g_replicas = reg.gauge(
            "autoscaler_replicas",
            "Attached replicas under autoscaler control")
        self._g_burn_fast = reg.gauge(
            "autoscaler_burn_fast",
            "Worst watched fast-window SLO burn rate at the last "
            "evaluation")
        self._g_burn_slow = reg.gauge(
            "autoscaler_burn_slow",
            "Worst watched slow-window SLO burn rate at the last "
            "evaluation")
        self._g_pending = reg.gauge(
            "autoscaler_pending_per_replica",
            "Fleet pending depth per attached replica at the last "
            "evaluation")
        self._g_replicas.set(len(router.attached_indices()))
        # the HTTP frontend's scenario hook: /stats and /autoscaler
        # surface this loop's view when the router carries one
        router.autoscaler = self

    # -- decision (hot-path roster: no I/O, no logging, no sleeps) ------

    def _burn_signal(self, report) -> tuple[float, float, str, str]:
        """Worst watched (fast, slow) burn pair and the (class,
        metric) that produced it. (0, 0) when nothing is tracked."""
        worst = (0.0, 0.0, "", "")
        if not report or not report.get("classes"):
            return worst
        wins = report["windows_s"]
        fast_k, slow_k = f"{wins[0]:g}", f"{wins[-1]:g}"
        for cname in self.cfg.classes:
            centry = report["classes"].get(cname)
            if centry is None:
                continue
            for metric in self.cfg.metrics:
                m = centry["metrics"].get(metric)
                if m is None:
                    continue
                fast = m["windows"][fast_k]["burn_rate"]
                slow = m["windows"][slow_k]["burn_rate"]
                # rank by the smaller of the pair: the multi-window
                # rule fires only when BOTH windows burn, so the
                # binding constraint is min(fast, slow)
                if min(fast, slow) > min(worst[0], worst[1]):
                    worst = (fast, slow, cname, metric)
        return worst

    def evaluate(self, now: float) -> tuple[str, str, str]:
        """(action, role, reason) for this instant: pure decision,
        no actuation, no side effects beyond the mirror gauges."""
        cfg = self.cfg
        n = len(self.router.attached_indices())
        pending = self.router.num_pending
        per_replica = pending / max(1, n)
        fast, slow, cname, metric = self._burn_signal(
            self.router.slo_report())
        self._g_replicas.set(n)
        self._g_burn_fast.set(fast)
        self._g_burn_slow.set(slow)
        self._g_pending.set(per_replica)
        if (self._last_action_at is not None
                and now - self._last_action_at < cfg.hold_s):
            return "hold", "colocated", "cooldown"
        burn_up = fast >= cfg.up_fast_burn and slow >= cfg.up_slow_burn
        queue_up = per_replica >= cfg.pending_high
        if (burn_up or queue_up) and n < cfg.max_replicas:
            role = self._role_for(metric if burn_up else "queue_wait")
            reason = (f"burn {cname}/{metric} fast={fast:.2f} "
                      f"slow={slow:.2f}" if burn_up
                      else f"pending/replica={per_replica:.1f}")
            return "up", role, reason
        if (n > cfg.min_replicas
                and fast <= cfg.down_fast_burn
                and slow <= cfg.down_slow_burn
                and per_replica <= cfg.pending_low):
            return ("down", "colocated",
                    f"idle: fast={fast:.2f} slow={slow:.2f} "
                    f"pending/replica={per_replica:.1f}")
        return "hold", "colocated", ""

    def _role_for(self, metric: str) -> str:
        """Which capacity a burn on ``metric`` asks for, on a
        disaggregated fleet; colocated fleets always add colocated."""
        if not getattr(self.router, "_disagg", False):
            return "colocated"
        if metric in _ROLE_PREFILL_METRICS:
            return "prefill"
        if metric in _ROLE_DECODE_METRICS:
            return "decode"
        return "colocated"

    # -- actuation -------------------------------------------------------

    def step(self, now: float | None = None) -> str:
        """One poll: evaluate and act. Returns the action taken
        ("up"/"down"/"hold"/"blocked")."""
        now = self._clock() if now is None else now
        with self._lock:
            action, role, reason = self.evaluate(now)
            if action == "up":
                return self._scale_up(now, role, reason)
            if action == "down":
                return self._scale_down(now, reason)
            return action

    def _record(self, now: float, action: str, role: str,
                reason: str) -> None:
        ev = ScaleEvent(t=now, action=action, role=role,
                        replicas=len(self.router.attached_indices()),
                        reason=reason)
        self.events.append(ev)
        _log.info("autoscaler %s role=%s replicas=%d (%s)",
                  action, role, ev.replicas, reason)

    def _scale_up(self, now: float, role: str, reason: str) -> str:
        replica = self.spawn(role)
        if replica is None:
            self._m_blocked.inc()
            self._record(now, "blocked", role,
                         f"spawn pool empty; wanted up: {reason}")
            return "blocked"
        self.router.add_replica(replica, role=role)
        self._last_action_at = now
        self._m_up.inc()
        self._g_replicas.set(len(self.router.attached_indices()))
        self._record(now, "up", role, reason)
        return "up"

    def _scale_down(self, now: float, reason: str) -> str:
        # victim: the least-loaded attached replica — cheapest
        # evacuation, and the affinity loss is smallest
        idxs = self.router.attached_indices()
        victim = min(
            idxs, key=lambda i: (self.router.replicas[i].num_active
                                 + self.router.replicas[i].num_pending))
        role = self.router.roles[victim]
        replica = self.router.remove_replica(
            victim, migrate=True, timeout=self.cfg.drain_timeout_s)
        if replica is None:
            self._m_blocked.inc()
            self._record(now, "blocked", role,
                         f"drain timeout on replica {victim}")
            return "blocked"
        self._last_action_at = now
        self._m_down.inc()
        self._g_replicas.set(len(self.router.attached_indices()))
        self._record(now, "down", role, reason)
        try:
            self.release(replica)
        except Exception:  # noqa: BLE001 — release is caller policy
            _log.exception("autoscaler release hook failed")
        return "down"

    # -- background loop -------------------------------------------------

    def start(self) -> "SLOBurnAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.cfg.poll_s):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — keep polling
                    _log.exception("autoscaler step failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- read path -------------------------------------------------------

    def stats(self) -> dict:
        cfg = self.cfg
        return {
            "replicas": len(self.router.attached_indices()),
            "min_replicas": cfg.min_replicas,
            "max_replicas": cfg.max_replicas,
            "hold_s": cfg.hold_s,
            "scale_up_total": int(self._m_up.value),
            "scale_down_total": int(self._m_down.value),
            "blocked_total": int(self._m_blocked.value),
            "burn_fast": self._g_burn_fast.value,
            "burn_slow": self._g_burn_slow.value,
            "pending_per_replica": self._g_pending.value,
            "events": [e.to_json() for e in self.events[-32:]]}

"""cloud_server_tpu — a TPU-native training & serving framework.

Built from scratch for TPU (JAX/XLA/pallas/pjit). The reference repository
(view-sonic/Cloud-Server @ v0) is an empty working tree (see SURVEY.md),
so the capability set comes from the round-1 driver re-scope recorded in
SURVEY.md §2b / §7.

Design principles:
  * Pure-functional models: parameters are plain pytrees, forward passes are
    pure functions — everything composes with jit/grad/scan/shard_map.
  * SPMD over a named `jax.sharding.Mesh` with canonical axes
    (dp, fsdp, pp, tp, sp, ep); XLA inserts the collectives.
  * Scan-over-layers with rematerialisation for compile speed and memory.
  * bfloat16 activations on the MXU, float32 master params/optimizer state.
"""

__version__ = "0.1.0"

from cloud_server_tpu.config import (  # noqa: F401
    MeshConfig,
    ModelConfig,
    TrainConfig,
)

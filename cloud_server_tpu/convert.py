"""Checkpoint conversion CLI: `python -m cloud_server_tpu.convert`.

Export a framework checkpoint to a HuggingFace LLaMA-family directory
(loadable with `transformers.AutoModelForCausalLM.from_pretrained`), the
inverse of `generate.py --hf-checkpoint`. Completes round-trip interop:
bring weights in, train/fine-tune here, take them back out.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cloud_server_tpu.convert",
        description="Export a framework checkpoint to a HuggingFace "
        "LLaMA-family directory.")
    p.add_argument("--config", required=True,
                   help="JSON config with the model section used at "
                   "training time")
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--step", type=int, help="checkpoint step (default latest)")
    p.add_argument("--out", required=True, metavar="DIR",
                   help="output HF checkpoint directory")
    p.add_argument("--ema", action="store_true",
                   help="export the EMA-averaged weights (needs a run "
                   "trained with ema_decay > 0)")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    from cloud_server_tpu.config import (MeshConfig, ModelConfig, from_json)
    from cloud_server_tpu.models.hf_convert import params_to_hf
    from cloud_server_tpu.parallel.mesh import make_mesh

    with open(args.config) as f:
        raw = json.load(f)
    model_cfg = from_json(ModelConfig, raw.get("model", {}))
    if model_cfg.num_experts >= 2:
        raise SystemExit(
            "HF export supports the dense LLaMA family only (the MoE "
            "layout has no LlamaForCausalLM equivalent)")

    mesh = make_mesh(MeshConfig())
    if args.ema:
        from cloud_server_tpu.training.checkpoint import restore_ema_params
        try:
            params = restore_ema_params(args.checkpoint_dir, model_cfg,
                                        mesh, step=args.step)
        except FileNotFoundError as e:
            raise SystemExit(str(e))
    else:
        from cloud_server_tpu.training.checkpoint import restore_params
        params = restore_params(args.checkpoint_dir, model_cfg, mesh,
                                step=args.step)

    state_dict = params_to_hf(params, model_cfg)

    import transformers

    hf_cfg = transformers.LlamaConfig(
        vocab_size=model_cfg.vocab_size,
        hidden_size=model_cfg.embed_dim,
        intermediate_size=model_cfg.mlp_dim,
        num_hidden_layers=model_cfg.num_layers,
        num_attention_heads=model_cfg.num_heads,
        num_key_value_heads=model_cfg.num_kv_heads,
        head_dim=model_cfg.head_dim,
        max_position_embeddings=model_cfg.max_seq_len,
        rms_norm_eps=model_cfg.norm_eps,
        rope_theta=model_cfg.rope_theta,
        tie_word_embeddings=model_cfg.tie_embeddings,
        attention_bias=False, mlp_bias=False, hidden_act="silu")
    if model_cfg.rope_scaling == "linear":
        hf_cfg.rope_scaling = {"rope_type": "linear",
                               "factor": model_cfg.rope_scaling_factor}
    elif model_cfg.rope_scaling == "llama3":
        hf_cfg.rope_scaling = {
            "rope_type": "llama3",
            "factor": model_cfg.rope_scaling_factor,
            "low_freq_factor": model_cfg.rope_low_freq_factor,
            "high_freq_factor": model_cfg.rope_high_freq_factor,
            "original_max_position_embeddings":
                model_cfg.rope_original_max_len}

    import torch

    model = transformers.LlamaForCausalLM(hf_cfg)
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in state_dict.items()},
        strict=False)
    # rotary buffers are recomputed, and with tied embeddings HF derives
    # lm_head.weight from the embedding (params_to_hf rightly omits it;
    # raw load_state_dict has no tying awareness). Anything else missing
    # is a bug.
    real_missing = [k for k in missing
                    if "rotary_emb" not in k
                    and not (model_cfg.tie_embeddings
                             and k == "lm_head.weight")]
    if real_missing or unexpected:
        raise SystemExit(
            f"export mismatch: missing={real_missing} "
            f"unexpected={unexpected}")
    model.save_pretrained(args.out)
    print(f"[convert] wrote HF checkpoint to {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

from cloud_server_tpu.training.checkpoint import (  # noqa: F401
    Checkpointer,
    abstract_train_state,
    restore_or_init,
)
from cloud_server_tpu.training.eval import (  # noqa: F401
    evaluate,
    make_eval_step,
)
from cloud_server_tpu.training.loop import LoopConfig, train_loop  # noqa: F401
from cloud_server_tpu.training.optim import make_optimizer  # noqa: F401
from cloud_server_tpu.training.train_step import (  # noqa: F401
    TrainState,
    init_train_state,
    make_train_step,
)

"""Sharded training step.

The whole step — forward, backward, optimizer update — is one `jit` with
explicit in/out shardings. XLA derives every collective (gradient
reduce-scatter/all-gather for FSDP, activation psums for TP) from the
sharding annotations; there is no hand-written gradient sync.

Gradient accumulation is a `lax.scan` over microbatches *inside* the jit,
so accumulation never leaves the device.
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import optax

from cloud_server_tpu.config import ModelConfig, TrainConfig
from cloud_server_tpu.models import transformer
from cloud_server_tpu.parallel.sharding import (
    DEFAULT_RULES, logical_to_sharding, spec_from_logical)
from cloud_server_tpu.training.optim import optimizer_for_module


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any


def state_shardings(model_cfg: ModelConfig, mesh: Mesh,
                    rules=DEFAULT_RULES,
                    loss_fn_module=transformer,
                    train_cfg: TrainConfig | None = None) -> TrainState:
    """Build the TrainState sharding pytree by abstract-evaluating init.

    `train_cfg` matters because optimizer-state STRUCTURE depends on it
    (ema_decay adds an EmaState to the chain) — callers building shardings
    for a real state must pass the same config that built its optimizer.
    """
    logical = loss_fn_module.param_logical_axes(model_cfg)
    param_sh = logical_to_sharding(logical, mesh, rules)

    # Optimizer state mirrors params; derive its sharding by matching
    # structure: any leaf of opt_state with the same shape as a param gets
    # the param's sharding, scalars are replicated.
    opt = optimizer_for_module(train_cfg or TrainConfig(), model_cfg,
                               loss_fn_module)
    params_shape = jax.eval_shape(
        partial(loss_fn_module.init_params, model_cfg), jax.random.key(0))
    opt_shape = jax.eval_shape(opt.init, params_shape)

    flat_params, _ = jax.tree.flatten(params_shape)
    flat_param_sh, _ = jax.tree.flatten(param_sh)
    shape_to_sh = {}
    shape_only = {}
    for p, s in zip(flat_params, flat_param_sh):
        shape_to_sh.setdefault((p.shape, p.dtype), s)
        shape_only.setdefault(p.shape, s)
    replicated = NamedSharding(mesh, P())

    def opt_leaf_sharding(leaf):
        # Shape-only fallback: the EMA tree is always float32, so with
        # bf16 master params its leaves match param shapes but not dtypes
        # — they must still shard like the params, not replicate.
        return shape_to_sh.get((leaf.shape, leaf.dtype),
                               shape_only.get(leaf.shape, replicated))

    opt_sh = jax.tree.map(opt_leaf_sharding, opt_shape)
    return TrainState(step=replicated, params=param_sh, opt_state=opt_sh)


def init_train_state(model_cfg: ModelConfig, train_cfg: TrainConfig,
                     mesh: Mesh, rng: jax.Array, rules=DEFAULT_RULES,
                     loss_fn_module=transformer) -> TrainState:
    """Initialise params + optimizer state *sharded* — each device only
    materialises its own shard (init runs under jit with out_shardings)."""
    shardings = state_shardings(model_cfg, mesh, rules,
                                loss_fn_module=loss_fn_module,
                                train_cfg=train_cfg)
    opt = optimizer_for_module(train_cfg, model_cfg, loss_fn_module)

    def init_fn(rng):
        params = loss_fn_module.init_params(model_cfg, rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    return jax.jit(init_fn, out_shardings=shardings)(rng)


def make_train_step(model_cfg: ModelConfig, train_cfg: TrainConfig,
                    mesh: Mesh, rules=DEFAULT_RULES,
                    loss_fn: Callable | None = None,
                    loss_fn_module=transformer):
    """Return a jitted (state, batch) -> (state, metrics) function.

    batch: {"tokens": (B, S) int32} with B the *global* batch size;
    arrays must be laid out with the returned `batch_sharding`.
    """
    if loss_fn is None:
        kwargs = {"z_loss_coef": train_cfg.z_loss_coef}
        sig = inspect.signature(loss_fn_module.next_token_loss).parameters
        if "aux_loss_coef" in sig:
            kwargs["aux_loss_coef"] = train_cfg.moe_aux_loss_coef
        if "router_z_coef" in sig:
            kwargs["router_z_coef"] = train_cfg.moe_router_z_coef
        loss_fn = partial(loss_fn_module.next_token_loss, **kwargs)
    opt = optimizer_for_module(train_cfg, model_cfg, loss_fn_module)
    shardings = state_shardings(model_cfg, mesh, rules, loss_fn_module,
                                train_cfg=train_cfg)
    # (B, S): batch over (dp, fsdp), sequence over sp — with sp > 1 every
    # activation downstream of the embedding (norms, MLP, fused CE) computes
    # S/sp per device; only ring attention sees the full sequence, via its
    # shard_map. XLA propagates the S-sharding from this input spec plus
    # the anchor constraint in transformer.forward_hidden.
    batch_spec = spec_from_logical(("batch", "sequence"), rules)
    batch_sharding = NamedSharding(mesh, batch_spec)
    replicated = NamedSharding(mesh, P())

    def grads_one_microbatch(params, batch):
        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch, model_cfg)
        return grads, metrics

    def step_fn(state: TrainState, batch: dict):
        nsteps = train_cfg.microbatch_steps
        if nsteps == 1:
            grads, metrics = grads_one_microbatch(state.params, batch)
        else:
            # (B, ...) -> (nsteps, B // nsteps, ...); scan accumulates.
            micro = jax.tree.map(
                lambda x: x.reshape((nsteps, x.shape[0] // nsteps) + x.shape[1:]),
                batch)

            def body(acc, mb):
                # Keep each microbatch sharded like the global batch.
                mb = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, batch_sharding),
                    mb)
                g, m = grads_one_microbatch(state.params, mb)
                return (jax.tree.map(jnp.add, acc[0], g),
                        jax.tree.map(jnp.add, acc[1], m)), None

            g0, m0 = grads_one_microbatch(
                state.params, jax.tree.map(lambda x: x[0], micro))
            (gsum, msum), _ = lax.scan(
                body, (g0, m0), jax.tree.map(lambda x: x[1:], micro))
            grads = jax.tree.map(lambda g: g / nsteps, gsum)
            metrics = jax.tree.map(lambda m: m / nsteps, msum)

        updates, new_opt = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt)
        return new_state, metrics

    jit_step = jax.jit(
        step_fn,
        in_shardings=(shardings, batch_sharding),
        out_shardings=(shardings, replicated),
        donate_argnums=(0,),
    )

    def step(state, batch):
        # Pin the registered mesh for trace-time consumers (constrain(),
        # attention_impl="ring"): a make_mesh() call between build and first
        # invocation must not rebind them to an unrelated mesh.
        from cloud_server_tpu.parallel.mesh import set_current_mesh
        set_current_mesh(mesh)
        return jit_step(state, batch)

    return step, batch_sharding

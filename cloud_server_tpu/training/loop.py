"""The full training loop: data → sharded step → metrics/ckpt/eval.

Orchestrates every subsystem the framework provides:
  * mesh construction + the jitted sharded train step (`train_step.py`),
  * the resumable sharded data pipeline (`data/loader.py`) with its state
    fast-forwarded from the restored step (the sampler is deterministic in
    (seed, step), so no separate data-state file is needed),
  * Orbax checkpointing with cadence/retention (`checkpoint.py`),
  * throughput/MFU accounting + JSONL logging (`utils/`),
  * periodic token-weighted evaluation (`eval.py`),
  * failure hooks — callables invoked every step with (step, state,
    metrics); a hook may raise to abort or return a replacement state
    (used by the NaN-guard / watchdog in `utils/failure.py`).

Blocking discipline: the loop only blocks on device results at log
boundaries, so up to `log_interval` steps stay in flight and host-side
work (data, logging, checkpoint serialisation) overlaps device compute.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax

from cloud_server_tpu.config import MeshConfig, ModelConfig, TrainConfig
from cloud_server_tpu.models import transformer
from cloud_server_tpu.data.loader import DataLoader
from cloud_server_tpu.parallel.mesh import make_mesh
from cloud_server_tpu.parallel.sharding import DEFAULT_RULES
from cloud_server_tpu.training.checkpoint import Checkpointer, restore_or_init
from cloud_server_tpu.training.eval import evaluate, make_eval_step
from cloud_server_tpu.training.train_step import make_train_step
from cloud_server_tpu.utils.logging import MetricLogger
from cloud_server_tpu.utils.metrics import (
    MetricAggregator, StepTimer, transformer_flops_per_token)


@dataclasses.dataclass(frozen=True)
class LoopConfig:
    """Knobs of the loop itself (cadences, paths) — everything that is not
    model/mesh/optimizer math."""

    log_interval: int = 10  # 0 => log only at the end of the run
    logdir: str | None = None
    checkpoint_dir: str | None = None
    checkpoint_interval: int = 500  # 0 => final save only
    max_checkpoints: int = 3
    async_checkpoint: bool = True
    eval_interval: int = 0  # 0 => no periodic eval
    eval_batches: int = 16
    data_prefetch: int = 2
    shuffle: bool = True


# A hook sees (step, state, metrics) after each train step. It may return
# None (observe only) or a replacement TrainState (e.g. rollback).
Hook = Callable[[int, object, dict], object | None]


def _beat_hooks(hooks: Sequence[Hook]) -> None:
    """Heartbeat protocol: hooks exposing `beat()` (e.g. a Watchdog) are
    beaten around long hook-free phases — eval sweeps, checkpoint writes —
    so those phases only need to finish within one watchdog timeout."""
    for h in hooks:
        beat = getattr(h, "beat", None)
        if callable(beat):
            beat()


def train_loop(model_cfg: ModelConfig, train_cfg: TrainConfig,
               dataset, *, mesh_cfg: MeshConfig | None = None,
               loop_cfg: LoopConfig | None = None, eval_dataset=None,
               rules=None, loss_fn_module=transformer, loss_fn=None,
               hooks: Sequence[Hook] = (), max_steps: int | None = None,
               mesh=None):
    """Run training to `train_cfg.total_steps`; returns the final TrainState.

    Resumes automatically from `loop_cfg.checkpoint_dir` when a checkpoint
    exists there (restoring onto the *current* mesh, which may differ from
    the save mesh — elastic resume). `max_steps` stops *this run* early
    (e.g. to simulate preemption) without touching `total_steps`, which
    the LR schedule depends on.
    """
    loop_cfg = loop_cfg or LoopConfig()
    rules = rules or DEFAULT_RULES
    # an explicit mesh (e.g. a hybrid ICI×DCN mesh from
    # parallel.distributed.make_hybrid_mesh) takes precedence over mesh_cfg
    mesh = mesh if mesh is not None else make_mesh(mesh_cfg or MeshConfig())

    step_fn, batch_sharding = make_train_step(
        model_cfg, train_cfg, mesh, rules=rules, loss_fn=loss_fn,
        loss_fn_module=loss_fn_module)

    ckpt = None
    if loop_cfg.checkpoint_dir is not None:
        ckpt = Checkpointer(
            loop_cfg.checkpoint_dir, max_to_keep=loop_cfg.max_checkpoints,
            save_interval_steps=max(1, loop_cfg.checkpoint_interval),
            async_save=loop_cfg.async_checkpoint)
        state, resumed = restore_or_init(
            ckpt, model_cfg, train_cfg, mesh, jax.random.key(train_cfg.seed),
            rules, loss_fn_module)
    else:
        from cloud_server_tpu.training.train_step import init_train_state
        state = init_train_state(model_cfg, train_cfg, mesh,
                                 jax.random.key(train_cfg.seed), rules,
                                 loss_fn_module)
        resumed = False
    start_step = int(jax.device_get(state.step))

    loader = DataLoader(dataset, train_cfg.batch_size, batch_sharding,
                        seed=train_cfg.seed, shuffle=loop_cfg.shuffle,
                        prefetch=loop_cfg.data_prefetch)
    # Deterministic data resume: one train step consumes one global batch,
    # so the sampler position is a pure function of the restored step.
    bpe = loader.sampler.batches_per_epoch
    loader.load_state_dict({"epoch": start_step // bpe,
                            "batch_in_epoch": start_step % bpe})

    eval_step = None
    if eval_dataset is not None and loop_cfg.eval_interval > 0:
        eval_step, eval_sharding = make_eval_step(
            model_cfg, mesh, rules, loss_fn_module, loss_fn=loss_fn)
        # prefetch=0: evaluate() stops mid-stream after eval_batches, and an
        # abandoned prefetch thread would block forever on its full queue,
        # leaking a thread + device batches per eval.
        eval_loader = DataLoader(
            eval_dataset, train_cfg.batch_size, eval_sharding,
            seed=train_cfg.seed, shuffle=False, prefetch=0)

    tokens_per_step = train_cfg.batch_size * train_cfg.seq_len
    timer = StepTimer(
        flops_per_token=transformer_flops_per_token(
            model_cfg, train_cfg.seq_len),
        window=max(1, 100 // max(1, loop_cfg.log_interval)))
    agg = MetricAggregator()
    logger = MetricLogger(loop_cfg.logdir)
    if resumed:
        print(f"[loop] resumed from step {start_step} "
              f"({loop_cfg.checkpoint_dir})")

    stop_at = train_cfg.total_steps if max_steps is None else min(
        train_cfg.total_steps, max_steps)
    data_it = iter(loader)
    step = last_logged = start_step
    try:
        while step < stop_at:
            batch = next(data_it)
            state, metrics = step_fn(state, batch)
            step += 1
            agg.update(metrics)

            for hook in hooks:
                replacement = hook(step, state, metrics)
                if replacement is not None:
                    state = replacement

            if ((loop_cfg.log_interval > 0
                 and step % loop_cfg.log_interval == 0) or step == stop_at):
                jax.block_until_ready(metrics["loss"])
                flushed = agg.flush()
                flushed.update(timer.tick(
                    tokens_per_step * (step - last_logged)))
                last_logged = step
                logger.log(step, flushed)

            if eval_step is not None and step % loop_cfg.eval_interval == 0:
                _beat_hooks(hooks)
                eval_loader.load_state_dict({"epoch": 0, "batch_in_epoch": 0})
                eval_metrics = evaluate(
                    state.params, iter(eval_loader), eval_step,
                    max_batches=loop_cfg.eval_batches)
                from cloud_server_tpu.training.optim import ema_params
                averaged = ema_params(state.opt_state)
                if averaged is not None:
                    eval_loader.load_state_dict(
                        {"epoch": 0, "batch_in_epoch": 0})
                    eval_metrics.update({
                        f"ema_{k}": v for k, v in evaluate(
                            averaged, iter(eval_loader), eval_step,
                            max_batches=loop_cfg.eval_batches).items()})
                logger.log(step, eval_metrics)
                _beat_hooks(hooks)

            # Only touch the checkpointer on-cadence: Checkpointer.save reads
            # state.step from device, which would force a per-step sync.
            if (ckpt is not None and loop_cfg.checkpoint_interval > 0
                    and step % loop_cfg.checkpoint_interval == 0):
                ckpt.save(state)
                _beat_hooks(hooks)
    except KeyboardInterrupt:
        # Preemption-style interrupt: the in-flight state is still valid —
        # persist it so the next launch resumes from here.
        if ckpt is not None:
            _beat_hooks(hooks)
            ckpt.save(state, force=True)
        raise
    else:
        if ckpt is not None:
            _beat_hooks(hooks)  # final save can outlast a watchdog window
            ckpt.save(state, force=True)
    finally:
        # Any other exception (e.g. a NaN-guard hook aborting) must NOT
        # save: it would checkpoint corrupt params and retention could
        # evict the last good checkpoint.
        if ckpt is not None:
            ckpt.close()
        logger.close()
    return state

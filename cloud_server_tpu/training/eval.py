"""Evaluation harness: jitted sharded eval step + dataset sweep.

The eval step reuses the model's `next_token_loss` with all auxiliary loss
coefficients at zero, so the reported number is pure token-level
cross-entropy; perplexity is `exp(mean nll)`. Aggregation is
token-weighted across batches (each batch contributes its masked token
count), which makes the result independent of batch size.
"""

from __future__ import annotations

import itertools
import math
from typing import Iterable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.models import transformer
from cloud_server_tpu.parallel.sharding import (
    DEFAULT_RULES, logical_to_sharding, spec_from_logical)


def make_eval_step(model_cfg: ModelConfig, mesh: Mesh, rules=DEFAULT_RULES,
                   loss_fn_module=transformer, loss_fn=None):
    """Return (eval_step, batch_sharding).

    eval_step(params, batch) -> {"nll_sum": f32, "n_tokens": f32,
    "n_correct": f32} — sums, not means, so the caller can aggregate
    exactly across batches of different effective (masked) sizes.

    `loss_fn` (same contract as the training one: (params, batch, cfg) ->
    (loss, metrics with "loss"/"accuracy")) keeps eval measuring the same
    objective as a custom training loss.
    """
    loss_fn = loss_fn or loss_fn_module.next_token_loss
    logical = loss_fn_module.param_logical_axes(model_cfg)
    param_sharding = logical_to_sharding(logical, mesh, rules)
    batch_sharding = NamedSharding(
        mesh, spec_from_logical(("batch", "sequence"), rules))
    replicated = NamedSharding(mesh, P())

    def eval_fn(params, batch):
        loss, metrics = loss_fn(params, batch, model_cfg)
        tokens = batch["tokens"]
        mask = batch.get("mask")
        n = (jnp.float32(tokens.shape[0] * (tokens.shape[1] - 1))
             if mask is None else mask[:, 1:].astype(jnp.float32).sum())
        # next_token_loss returns the *mean* CE (aux coefs default to 0 for
        # the dense family; MoE adds load-balance — recompute from the pure
        # "loss" metric, which is CE-only in both families).
        ce = metrics["loss"]
        return {"nll_sum": ce * n, "n_tokens": n,
                "n_correct": metrics["accuracy"] * n}

    jit_step = jax.jit(eval_fn, in_shardings=(param_sharding, batch_sharding),
                       out_shardings=replicated)

    def step(params, batch):
        # Pin the registered mesh for trace-time consumers (constrain(),
        # attention_impl="ring"): a make_mesh() call between build and first
        # invocation must not rebind them to an unrelated mesh.
        from cloud_server_tpu.parallel.mesh import set_current_mesh
        set_current_mesh(mesh)
        return jit_step(params, batch)

    return step, batch_sharding


def evaluate(params, batches: Iterable[dict], eval_step,
             max_batches: int | None = None) -> dict[str, float]:
    """Sweep `batches` through `eval_step`; return token-weighted metrics.

    batches: iterable of {"tokens": (B, S)} already laid out with the
    sharding `make_eval_step` returned. Stops after `max_batches` if given.
    """
    nll = 0.0
    n_tokens = 0.0
    n_correct = 0.0
    if max_batches is not None:
        batches = itertools.islice(batches, max_batches)
    for batch in batches:
        out = jax.device_get(eval_step(params, batch))
        nll += float(out["nll_sum"])
        n_tokens += float(out["n_tokens"])
        n_correct += float(out["n_correct"])
    if n_tokens == 0:
        return {"eval_loss": float("nan"), "eval_ppl": float("nan"),
                "eval_accuracy": float("nan"), "eval_tokens": 0.0}
    mean_nll = nll / n_tokens
    return {"eval_loss": mean_nll,
            "eval_ppl": math.exp(min(mean_nll, 30.0)),
            "eval_accuracy": n_correct / n_tokens,
            "eval_tokens": n_tokens}

"""Checkpoint / resume — Orbax-backed, sharding-aware.

Save path: the whole `TrainState` pytree goes through Orbax's standard
(tensorstore/OCDBT) handler; with async enabled the device arrays are
snapshotted to host and serialisation overlaps the next training steps.

Restore path: the caller supplies the *target* mesh/shardings (via
`abstract_train_state`), so each process reads only the shards it owns
straight from the checkpoint — no full-replica materialisation on any
host. The restore mesh may differ from the save mesh (Orbax reshards on
read), which is what makes elastic resume — restoring on a different
topology after a failure — work without a conversion step.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

import orbax.checkpoint as ocp

from cloud_server_tpu.config import ModelConfig, TrainConfig
from cloud_server_tpu.models import transformer
from cloud_server_tpu.parallel.sharding import (
    DEFAULT_RULES, logical_to_sharding)
from cloud_server_tpu.training.optim import optimizer_for_module
from cloud_server_tpu.training.train_step import TrainState, state_shardings


def abstract_train_state(model_cfg: ModelConfig, train_cfg: TrainConfig,
                         mesh, rules=DEFAULT_RULES,
                         loss_fn_module=transformer) -> TrainState:
    """TrainState of ShapeDtypeStructs carrying the target mesh's shardings.

    This is the `target` a sharded restore needs: shape/dtype say *what* to
    read, the attached NamedSharding says *where* each shard lands.
    """
    shardings = state_shardings(model_cfg, mesh, rules, loss_fn_module,
                                train_cfg=train_cfg)
    opt = optimizer_for_module(train_cfg, model_cfg, loss_fn_module)

    def init_fn(rng):
        params = loss_fn_module.init_params(model_cfg, rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    shapes = jax.eval_shape(init_fn, jax.random.key(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


class Checkpointer:
    """Step-indexed checkpoint manager for TrainState pytrees.

    Thin policy layer over `ocp.CheckpointManager`: retention
    (`max_to_keep`), cadence (`save_interval_steps` — `save()` is a no-op
    off-cadence so the train loop can call it every step), and async save.
    """

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory)), options=options)

    # -- save ---------------------------------------------------------------

    def save(self, state: TrainState, *, metrics: dict | None = None,
             force: bool = False) -> bool:
        """Save `state` at its own step counter. Returns False when skipped
        (off-cadence for save_interval_steps, or step already saved).

        When the optimizer tracks a param EMA (TrainConfig.ema_decay > 0),
        the EMA tree is ALSO written as its own checkpoint item ("ema",
        next to the usual "default") so serving can restore just that one
        params-sized tree — the cost of one duplicated tree on disk buys
        an eval/serve path that never touches optimizer moments."""
        import sys

        from cloud_server_tpu.training.optim import ema_params
        step = int(jax.device_get(state.step))
        if step in self._mngr.all_steps():
            return False  # even force=True must not collide with a done save
        ema = ema_params(state.opt_state)
        # The manager locks into the item layout of the first step on disk.
        # Detect a legacy single-item directory (pre-EMA steps, no "ema"
        # item dir) by inspecting the on-disk layout — the same signal
        # restore() uses — rather than catching ValueError, which would
        # also swallow genuine tree/structure failures in StandardSave.
        legacy_single_item = False
        if ema is not None:
            root = os.fspath(self._mngr.directory)
            # Only FINALIZED steps count: an in-flight async save lives in
            # a tmp-suffixed directory (no `root/step/` yet), so a
            # composite save still finalizing must not flip this run's
            # classification to legacy.
            prior = [s for s in self._mngr.all_steps()
                     if os.path.isdir(os.path.join(root, str(s)))]
            legacy_single_item = bool(prior) and not any(
                os.path.isdir(os.path.join(root, str(s), "ema"))
                for s in prior)
            if legacy_single_item:
                print("[checkpoint] directory predates the 'ema' item; "
                      "saving state only (ema still restorable via the "
                      "full state)", file=sys.stderr)
        if ema is None or legacy_single_item:
            return self._mngr.save(step, args=ocp.args.StandardSave(state),
                                   metrics=metrics, force=force)
        return self._mngr.save(
            step, args=ocp.args.Composite(
                default=ocp.args.StandardSave(state),
                ema=ocp.args.StandardSave(ema)),
            metrics=metrics, force=force)

    # -- restore ------------------------------------------------------------

    def restore(self, target: TrainState, step: int | None = None) -> TrainState:
        """Sharded restore. `target` comes from `abstract_train_state` (or is
        a concrete TrainState, whose shardings are reused). Restores the
        latest step unless `step` is given."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self._mngr.directory}")
        # Layout is decided by what save() wrote, visible on disk: an
        # "ema" item dir means named-items layout. (Detecting by catching
        # ValueError would also swallow real tree-structure mismatches.)
        has_ema_item = os.path.isdir(
            os.path.join(os.fspath(self._mngr.directory), str(step), "ema"))
        try:
            if has_ema_item:
                return self._mngr.restore(
                    step, args=ocp.args.Composite(
                        default=ocp.args.StandardRestore(target)))["default"]
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(target))
        except ValueError as e:
            raise ValueError(
                f"restore of step {step} failed with a structure mismatch. "
                "If TrainConfig.ema_decay was toggled since this checkpoint "
                "was written, the optimizer-state tree no longer matches — "
                "resume with the original ema_decay setting (or restore "
                "params-only via restore_params and re-init the optimizer)."
            ) from e

    # -- bookkeeping --------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mngr.all_steps())

    def wait(self) -> None:
        """Block until in-flight async saves are durable."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _abstract_sharded_params(model_cfg: ModelConfig, mesh,
                             rules=DEFAULT_RULES, loss_fn_module=transformer,
                             dtype=None):
    """Sharded ShapeDtypeStruct tree for a module's params — the restore
    `target` both params-style restores build. `dtype` overrides every
    leaf dtype (the EMA accumulator is float32 regardless of param_dtype).
    """
    from functools import partial

    logical = loss_fn_module.param_logical_axes(model_cfg)
    shardings = logical_to_sharding(logical, mesh, rules)
    shapes = jax.eval_shape(partial(loss_fn_module.init_params, model_cfg),
                            jax.random.key(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, dtype or s.dtype,
                                           sharding=sh),
        shapes, shardings)


def _latest_step(directory: str) -> int:
    try:
        steps = ocp.utils.checkpoint_steps(directory)
    except ValueError:  # older orbax raises instead of returning [] for
        steps = []      # a directory that does not exist
    if not steps:
        raise FileNotFoundError(f"no checkpoint found under {directory}")
    return max(steps)


def restore_params(checkpoint_dir: str | os.PathLike, model_cfg: ModelConfig,
                   mesh, *, step: int | None = None, rules=DEFAULT_RULES,
                   loss_fn_module=transformer):
    """Params-only sharded restore — no optimizer-moment IO.

    For serving and fine-tune warm starts: reads just the `params` subtree
    of a saved TrainState (~1/3 of the checkpoint bytes; Adam's two moment
    trees are never touched), sharded straight onto `mesh`.
    """
    directory = os.path.abspath(os.fspath(checkpoint_dir))
    if step is None:
        step = _latest_step(directory)
    path = os.path.join(directory, str(step), "default")
    target = {"params": _abstract_sharded_params(model_cfg, mesh, rules,
                                                 loss_fn_module)}
    import inspect
    if "partial_restore" in inspect.signature(
            ocp.args.PyTreeRestore.__init__).parameters:
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
            out = ckptr.restore(
                path,
                args=ocp.args.PyTreeRestore(item=target,
                                            restore_args=restore_args,
                                            partial_restore=True))
        return out["params"]
    # older orbax cannot restore a subtree of a saved tree: fall back to
    # a full host restore and shard just the params onto `mesh` (reads
    # the optimizer bytes too — correctness identical, IO not minimal)
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        out = ckptr.restore(path)
    return jax.tree.map(lambda sds, x: jax.device_put(x, sds.sharding),
                        target["params"], out["params"])


def restore_ema_params(checkpoint_dir: str | os.PathLike,
                       model_cfg: ModelConfig, mesh, *,
                       step: int | None = None, rules=DEFAULT_RULES,
                       loss_fn_module=transformer):
    """Sharded restore of the EMA param tree — the "ema" item
    `Checkpointer.save` writes when TrainConfig.ema_decay > 0. One
    params-sized read; no optimizer-moment or raw-param IO. The tree is
    float32 (the EMA accumulator dtype) and drop-in wherever params go
    (forwards cast to cfg.dtype at use)."""
    directory = os.path.abspath(os.fspath(checkpoint_dir))
    if step is None:
        step = _latest_step(directory)
    item_dir = os.path.join(directory, str(step), "ema")
    if not os.path.isdir(item_dir):
        raise FileNotFoundError(
            f"checkpoint step {step} has no 'ema' item — was the run "
            "trained with TrainConfig.ema_decay > 0 (and saved by this "
            "version)?")

    target = _abstract_sharded_params(model_cfg, mesh, rules, loss_fn_module,
                                      dtype=jnp.float32)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        return ckptr.restore(item_dir, args=ocp.args.StandardRestore(target))


def restore_or_init(ckpt: Checkpointer, model_cfg: ModelConfig,
                    train_cfg: TrainConfig, mesh, rng: jax.Array,
                    rules=DEFAULT_RULES,
                    loss_fn_module=transformer) -> tuple[TrainState, bool]:
    """The resume entry point a train loop calls once at startup: restore
    the latest checkpoint onto `mesh` if one exists, else init fresh.
    Returns (state, resumed)."""
    from cloud_server_tpu.training.train_step import init_train_state
    if ckpt.latest_step() is not None:
        target = abstract_train_state(model_cfg, train_cfg, mesh, rules,
                                      loss_fn_module)
        return ckpt.restore(target), True
    return init_train_state(model_cfg, train_cfg, mesh, rng, rules,
                            loss_fn_module), False

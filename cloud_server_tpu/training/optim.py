"""Optimizer construction.

AdamW with linear warmup → cosine decay and global-norm clipping. Weight
decay is masked off norm scales, matching standard LLM practice. Optimizer
state inherits the parameters' sharding (same pytree structure), so FSDP
shards moments for free.

The default is a *fused* AdamW: one elementwise pass per parameter leaf
doing clip + moment update + bias correction + decoupled weight decay +
learning-rate scale together. NOTE: the optimizer-state pytree is
`FusedAdamWState(count, mu, nu)`, a different structure from the optax
chain tuple — full-state checkpoints written before this change cannot
resume the optimizer (params-only restore is unaffected). The equivalent `optax.chain(clip_by_
global_norm, adamw)` materialises a full intermediate update tree per
stage (~2.5x the HBM traffic of the fused pass); on a 330M-param bench
step the chain costs ~26 ms vs ~13 ms fused. Numerics match optax's adamw
exactly (bias correction with t starting at 1, schedule evaluated at the
pre-increment count, eps outside the sqrt) — `tests/test_train.py` asserts
parity leaf-by-leaf.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from cloud_server_tpu.config import TrainConfig


def _decay_mask(params):
    def is_decayed(path, _):
        path_str = "/".join(p.key for p in path)
        return "norm" not in path_str

    return jax.tree_util.tree_map_with_path(is_decayed, params)


def make_schedule(cfg: TrainConfig) -> optax.Schedule:
    if cfg.lr_schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
            end_value=cfg.learning_rate * 0.1,
        )
    if cfg.lr_schedule == "constant":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.learning_rate,
                                   max(1, cfg.warmup_steps)),
             optax.constant_schedule(cfg.learning_rate)],
            boundaries=[cfg.warmup_steps])
    if cfg.lr_schedule == "wsd":
        # warmup -> stable at peak -> linear cooldown to ~0 over the last
        # lr_decay_frac of total_steps
        decay_steps = max(1, int(cfg.total_steps * cfg.lr_decay_frac))
        stable_steps = max(0, cfg.total_steps - cfg.warmup_steps
                           - decay_steps)
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.learning_rate,
                                   max(1, cfg.warmup_steps)),
             optax.constant_schedule(cfg.learning_rate),
             optax.linear_schedule(cfg.learning_rate,
                                   cfg.learning_rate * 0.01, decay_steps)],
            boundaries=[cfg.warmup_steps, cfg.warmup_steps + stable_steps])
    raise ValueError(f"unknown lr_schedule: {cfg.lr_schedule!r}")


class FusedAdamWState(NamedTuple):
    count: jnp.ndarray  # () int32, number of completed updates
    mu: Any
    nu: Any


def fused_adamw(cfg: TrainConfig, eps: float = 1e-8
                ) -> optax.GradientTransformation:
    """Single-pass AdamW == optax.chain(clip_by_global_norm, adamw(...))."""
    sched = make_schedule(cfg)

    def init(params):
        zeros = lambda p: jnp.zeros_like(p)
        return FusedAdamWState(count=jnp.zeros((), jnp.int32),
                               mu=jax.tree.map(zeros, params),
                               nu=jax.tree.map(zeros, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("fused_adamw requires params for weight decay")
        count_inc = state.count + 1
        # optax.clip_by_global_norm semantics: scale by clip/norm when
        # norm > clip (trust-ratio style, no epsilon in the denominator).
        gnorm = optax.global_norm(grads)
        scale = jnp.minimum(1.0, cfg.grad_clip_norm / jnp.maximum(
            gnorm, 1e-30))
        # scale_by_learning_rate's inner schedule sees the pre-increment
        # count (its own state starts at 0), hence sched(state.count).
        lr = sched(state.count)
        b1, b2 = cfg.beta1, cfg.beta2
        bc1 = 1.0 - b1 ** count_inc.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count_inc.astype(jnp.float32)
        decay_mask = _decay_mask(params)

        def leaf(g, m, v, p, decayed):
            g = g * scale
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if decayed:
                u = u + cfg.weight_decay * p
            return m, v, -lr * u

        flat_g, treedef = jax.tree.flatten(grads)
        flat = [leaf(g, m, v, p, d) for g, m, v, p, d in zip(
            flat_g, jax.tree.leaves(state.mu), jax.tree.leaves(state.nu),
            jax.tree.leaves(params), jax.tree.leaves(decay_mask))]
        mu = jax.tree.unflatten(treedef, [f[0] for f in flat])
        nu = jax.tree.unflatten(treedef, [f[1] for f in flat])
        updates = jax.tree.unflatten(treedef, [f[2] for f in flat])
        return updates, FusedAdamWState(count=count_inc, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def reference_adamw(cfg: TrainConfig) -> optax.GradientTransformation:
    """The unfused optax chain fused_adamw must match (kept for tests)."""
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(
            learning_rate=make_schedule(cfg),
            b1=cfg.beta1,
            b2=cfg.beta2,
            weight_decay=cfg.weight_decay,
            mask=_decay_mask,
        ),
    )


class EmaState(NamedTuple):
    ema: Any  # params-like pytree


def ema_of_params(decay: float) -> optax.GradientTransformation:
    """Track an exponential moving average of the *post-update* params.

    Chain this LAST after the real optimizer: its `update` sees the final
    deltas, reconstructs new_params = params + updates, and folds them into
    the average (updates pass through untouched). The EMA tree mirrors the
    param tree structure, so it inherits param shardings in
    `state_shardings`, and is checkpointed with the rest of the optimizer
    state. Initialised at the initial params (no bias correction — the
    standard LLM-eval choice: after ~3/(1-decay) steps the init's weight
    is negligible).

    The accumulator is ALWAYS float32: with bf16 master params a typical
    decay (0.99+) makes the per-step contribution (1-decay)*p smaller than
    bf16 resolution, so a same-dtype average would silently stay frozen at
    its init.
    """

    def init(params):
        return EmaState(ema=jax.tree.map(
            lambda p: jnp.asarray(p, jnp.float32), params))

    def update(updates, state, params):
        if params is None:
            raise ValueError("ema_of_params requires params")
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p.astype(e.dtype),
            state.ema, new_params)
        return updates, EmaState(ema=ema)

    return optax.GradientTransformation(init, update)


def ema_params(opt_state):
    """Pull the EMA param tree out of an optimizer state (wherever the
    EmaState sits in the chain). Returns None when EMA is disabled."""
    flat = jax.tree.flatten(
        opt_state, is_leaf=lambda x: isinstance(x, EmaState))[0]
    for leaf in flat:
        if isinstance(leaf, EmaState):
            return leaf.ema
    return None


def make_optimizer(cfg: TrainConfig,
                   param_labels=None) -> optax.GradientTransformation:
    """param_labels: optional pytree (matching params) of "trainable" /
    "frozen" strings — frozen params get `set_to_zero` and allocate no
    moments (the LoRA fine-tuning path; see models/lora.py).

    cfg.ema_decay > 0 appends `ema_of_params` to the chain (for LoRA this
    averages the full tree; frozen leaves converge to their fixed values
    after the warm-in window)."""
    opt = fused_adamw(cfg)
    if param_labels is not None:
        opt = optax.multi_transform(
            {"trainable": opt, "frozen": optax.set_to_zero()}, param_labels)
    if cfg.ema_decay > 0.0:
        opt = optax.chain(opt, ema_of_params(cfg.ema_decay))
    return opt


def optimizer_for_module(train_cfg: TrainConfig, model_cfg, loss_fn_module):
    """The one place that decides a module's optimizer structure: modules
    exposing `param_labels(model_cfg)` (e.g. the LoRA wrapper) get the
    label-masked variant. Everything that must agree on optimizer *state
    structure* (train step, init, checkpoint targets) goes through here."""
    labels_fn = getattr(loss_fn_module, "param_labels", None)
    labels = labels_fn(model_cfg) if labels_fn is not None else None
    return make_optimizer(train_cfg, param_labels=labels)

"""Optimizer construction (optax).

AdamW with linear warmup → cosine decay and global-norm clipping. Weight
decay is masked off norm scales, matching standard LLM practice. Optimizer
state inherits the parameters' sharding (same pytree structure), so FSDP
shards moments for free.
"""

from __future__ import annotations

import jax
import optax

from cloud_server_tpu.config import TrainConfig


def _decay_mask(params):
    def is_decayed(path, _):
        path_str = "/".join(p.key for p in path)
        return "norm" not in path_str

    return jax.tree_util.tree_map_with_path(is_decayed, params)


def make_schedule(cfg: TrainConfig) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )


def make_optimizer(cfg: TrainConfig,
                   param_labels=None) -> optax.GradientTransformation:
    """param_labels: optional pytree (matching params) of "trainable" /
    "frozen" strings — frozen params get `set_to_zero` and allocate no
    moments (the LoRA fine-tuning path; see models/lora.py)."""
    opt = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(
            learning_rate=make_schedule(cfg),
            b1=cfg.beta1,
            b2=cfg.beta2,
            weight_decay=cfg.weight_decay,
            mask=_decay_mask,
        ),
    )
    if param_labels is None:
        return opt
    return optax.multi_transform(
        {"trainable": opt, "frozen": optax.set_to_zero()}, param_labels)


def optimizer_for_module(train_cfg: TrainConfig, model_cfg, loss_fn_module):
    """The one place that decides a module's optimizer structure: modules
    exposing `param_labels(model_cfg)` (e.g. the LoRA wrapper) get the
    label-masked variant. Everything that must agree on optimizer *state
    structure* (train step, init, checkpoint targets) goes through here."""
    labels_fn = getattr(loss_fn_module, "param_labels", None)
    labels = labels_fn(model_cfg) if labels_fn is not None else None
    return make_optimizer(train_cfg, param_labels=labels)

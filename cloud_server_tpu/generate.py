"""Text generation CLI: `python -m cloud_server_tpu.generate`.

Loads model params from a training checkpoint (or random-inits for smoke
runs), tokenizes prompts, and serves them through the paged
continuous-batching server (`PagedInferenceServer` — block-table KV,
radix prefix reuse, chunked prefill, optional in-server speculative
decoding via `--spec-drafts`). `--contiguous` selects the legacy
fixed-slot `InferenceServer` instead. The tokenizer is byte-level by
default or a local HuggingFace `tokenizer.json` via `--tokenizer`.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cloud_server_tpu.generate",
        description="Generate text from a trained checkpoint.")
    p.add_argument("--config", help="JSON config with the model section "
                   "used at training time")
    p.add_argument("--checkpoint-dir",
                   help="training checkpoint directory (omit: random init)")
    p.add_argument("--hf-checkpoint", metavar="DIR",
                   help="local HuggingFace LLaMA-family checkpoint "
                   "directory to serve (mutually exclusive with "
                   "--checkpoint-dir; a --config model section may still "
                   "override behavioral fields like dtype/attention_impl — "
                   "structural fields that contradict the checkpoint are "
                   "rejected)")
    p.add_argument("--step", type=int, help="checkpoint step (default latest)")
    p.add_argument("--tokenizer", default="byte",
                   help='"byte" or a local tokenizer.json path')
    p.add_argument("--prompt", action="append", default=[],
                   help="prompt text (repeatable); '-' reads lines from stdin")
    p.add_argument("--max-new", type=int, default=128)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-len", type=int, default=0,
                   help="server cache length (default: fits prompt+max-new)")
    p.add_argument("--add-bos", action="store_true",
                   help="prepend BOS to prompts (only if training data "
                   "contained BOS — prepare_corpus does not emit it)")
    p.add_argument("--quantize", action="store_true",
                   help="serve with int8 weight-only quantization (halves "
                   "the weight bytes streamed per decode step)")
    p.add_argument("--kv-cache-int8", action="store_true",
                   help="store the KV cache int8-quantized (halves cache "
                   "memory; the scales fold into the attention math, so "
                   "there is no dequantized cache copy)")
    p.add_argument("--ema", action="store_true",
                   help="serve the EMA-averaged weights from a checkpoint "
                   "trained with ema_decay > 0 (reads the checkpoint's "
                   "'ema' item — one params-sized restore)")
    p.add_argument("--prefix", metavar="TEXT",
                   help="shared prompt prefix (e.g. a system prompt): its "
                   "KV is prefilled once and cached; prompts extending it "
                   "only run their remainder (prefix caching). Applies to "
                   "batch and --serve-http serving")
    p.add_argument("--serve-http", type=int, metavar="PORT", default=None,
                   help="instead of batch generation, run the continuous-"
                   "batching server behind an HTTP streaming endpoint "
                   "(POST /generate, ndjson token stream; GET /healthz)")
    p.add_argument("--decode-chunk", type=int, default=1,
                   help="decode steps per scheduler iteration (multi-token "
                   "scheduling; >1 amortises host sync at the cost of "
                   "admission latency)")
    p.add_argument("--contiguous", action="store_true",
                   help="serve through the legacy fixed-slot contiguous "
                   "server instead of the paged server (no paging, no "
                   "radix prefix reuse, no chunked prefill, no in-server "
                   "speculation; supports --prefix single-prefix caching)")
    p.add_argument("--max-slots", type=int, default=8,
                   help="concurrent request slots in the server")
    p.add_argument("--spec-drafts", type=int, default=0,
                   help="paged server only: in-server speculative decoding "
                   "with N n-gram draft tokens per round (exact accept "
                   "rule — output distribution unchanged; wins on "
                   "repetition-heavy output)")
    p.add_argument("--spec-control", metavar="FILE_OR_JSON",
                   default=None,
                   help="adaptive speculative decoding knobs (JSON "
                   "object/string or file path: low/high accept-rate "
                   "hysteresis, ewma, cooldown, probe_period, initial "
                   "draft length — inference/spec_control.py). Omitted: "
                   "the default adaptive controller whenever "
                   "speculation is on; 'off' pins the fixed "
                   "--spec-drafts length")
    p.add_argument("--page-size", type=int, default=128,
                   help="paged server: tokens per KV page (multiple of 128 "
                   "for the pallas decode kernel on TPU)")
    p.add_argument("--num-pages", type=int, default=0,
                   help="paged server: page pool size (0 = the HBM the "
                   "contiguous layout would reserve: "
                   "max_slots * max_context / page_size)")
    p.add_argument("--prefill-chunk", type=int, default=256,
                   help="paged server: admission window width — long "
                   "prompts prefill in chunks this wide, interleaved with "
                   "decode dispatches so inter-token latency stays bounded")
    p.add_argument("--scheduler", choices=["mixed", "alternating"],
                   default="mixed",
                   help="paged server scheduling under admission churn: "
                   "'mixed' (default) fuses chunked prefills and decode "
                   "rows into one token-budget dispatch per iteration "
                   "(stall-free — decodes advance during every prefill); "
                   "'alternating' keeps separate prefill and decode "
                   "dispatches (the pre-mixed behavior)")
    p.add_argument("--no-overlap", action="store_true",
                   help="disable the async double-buffered scheduler "
                   "(launch-ahead pipelining): by default each "
                   "iteration's host policy work — sweep, QoS/DRR "
                   "admission, deadline checks, the numpy dispatch "
                   "build — runs WHILE the device executes the "
                   "previous iteration's program, leaving only the "
                   "commit on the serialized path. This flag restores "
                   "the strictly sequential plan->dispatch->sync->"
                   "commit loop (byte-identical pre-overlap behavior; "
                   "outputs are token-identical either way)")
    p.add_argument("--mixed-token-budget", type=int, default=0,
                   help="mixed scheduler: tokens per fused iteration "
                   "(decode rows first, prefill fills the rest; 0 = auto: "
                   "max_slots * (decode window * decode_chunk + "
                   "prefill_chunk), i.e. work-conserving — set lower to "
                   "trade admission speed for a per-iteration ITL bound)")
    p.add_argument("--allocation", choices=["ondemand", "reserve"],
                   default="ondemand",
                   help="paged server page policy: 'ondemand' grows "
                   "chains per dispatch and preempts the youngest slot "
                   "on pool exhaustion (higher concurrency per GB); "
                   "'reserve' pre-reserves prompt+max_new at admission "
                   "(no preemption)")
    p.add_argument("--decode-impl", choices=["xla", "pallas"], default=None,
                   help="decode-attention implementation override; "
                   "'pallas' selects the paged-attention kernel "
                   "(paged server on TPU — length-bounded page reads beat "
                   "the XLA gather on ragged contexts)")
    p.add_argument("--draft-config", metavar="JSON",
                   help="speculative decoding with a small draft model "
                   "sharing the tokenizer (JSON config, model section). "
                   "Batch mode: the standalone speculative batch API. "
                   "--serve-http (paged): IN-SERVER draft-model "
                   "speculation — the draft keeps its own paged cache "
                   "and proposes --num-draft tokens per round")
    p.add_argument("--draft-checkpoint-dir",
                   help="draft model checkpoint (omit: random init — only "
                   "useful for smoke tests)")
    p.add_argument("--num-draft", type=int, default=4,
                   help="draft tokens proposed per speculative round")
    p.add_argument("--access-log", metavar="PATH", nargs="?",
                   const="stderr", default=None,
                   help="with --serve-http: structured JSON access log "
                   "(method, path, status, duration, request id), one "
                   "line per request — to PATH (JSONL file) or, with no "
                   "value, stderr. Off by default.")
    p.add_argument("--profiler-port", type=int, default=None,
                   metavar="PORT",
                   help="with --serve-http: expose the jax profiler "
                   "server on PORT for on-demand remote capture "
                   "(tensorboard profile), alongside the HTTP "
                   "front-end's own POST /debug/trace")
    p.add_argument("--flight-recorder", type=int, default=0,
                   metavar="N",
                   help="paged server: per-iteration flight-recorder "
                   "ring size for /stats post-mortems (0 = config "
                   "default)")
    p.add_argument("--qos-config", metavar="FILE_OR_JSON", default=None,
                   help="multi-tenant QoS: a JSON file path (or inline "
                   "JSON object) declaring per-tenant weights, priority "
                   "classes, token-bucket rate limits, pending bounds, "
                   "and API-key mappings (schema: docs/serving.md). "
                   "Enables weighted fair-share admission, priority "
                   "preemption, and per-tenant 429s; omitted, the "
                   "server runs the byte-identical single-tenant FIFO "
                   "paths")
    p.add_argument("--slo-config", metavar="FILE_OR_JSON", default=None,
                   help="per-priority-class SLO targets: a JSON file "
                   "path (or inline JSON object) declaring per-class "
                   "latency targets (ttft/itl/queue_wait/e2e), "
                   "attainment objectives, and rolling windows (schema: "
                   "inference/slo.py). Surfaced via GET /slo and the "
                   "slo_attainment/slo_burn_rate gauges; omitted, SLO "
                   "tracking is disabled entirely")
    p.add_argument("--fault-plan", metavar="FILE_OR_JSON", default=None,
                   help="deterministic fault injection: a JSON file "
                   "path (or inline JSON object) arming named fault "
                   "sites (submit_reject/dispatch/iteration_stall/"
                   "wedge/alloc_famine) with seeded after/count/p "
                   "windows (schema: inference/faults.py). Proves "
                   "recovery paths — router failover, breakers, "
                   "_fail_all — against a live server; omitted, "
                   "injection is disabled entirely")
    p.add_argument("--brownout", metavar="FILE_OR_JSON", default=None,
                   help="overload brownout (paged server, needs "
                   "--qos-config): a JSON file path (or inline JSON "
                   "object) with OverloadDetector thresholds over "
                   "pending age / budget utilization / host_gap_frac, "
                   "hysteresis, and per-level shed classes (schema: "
                   "inference/faults.py). Sheds best_effort/batch "
                   "admissions with jittered Retry-After 429s before "
                   "the interactive SLO burns")
    p.add_argument("--trace-sample-rate", type=float, default=0.0,
                   metavar="RATE",
                   help="per-request distributed tracing: head-based "
                   "sampling probability in [0, 1]. Sampled requests "
                   "carry span trees (GET /debug/requests/<id>, "
                   "Perfetto export via GET /traces, W3C traceparent "
                   "in/out). 0 (default) disables tracing entirely "
                   "(unless --trace-tail-capacity keeps the recorder "
                   "alive for tail retention)")
    p.add_argument("--trace-capacity", type=int, default=256,
                   metavar="N",
                   help="finished-trace ring size: how many completed "
                   "head-sampled span trees stay inspectable "
                   "(GET /traces; default 256)")
    p.add_argument("--trace-tail-capacity", type=int, default=0,
                   metavar="N",
                   help="tail-based trace retention: keep up to N span "
                   "trees of ANOMALOUS head-unsampled requests "
                   "(failed / deadline-expired / cancelled / migrated "
                   "/ SLO-violating / repeatedly-preempted / finished "
                   "inside an open anomaly window) in a separate ring. "
                   "Works at any --trace-sample-rate, including 0 — "
                   "e.g. 1%% head sampling plus a tail ring means "
                   "broken requests are ALWAYS inspectable. 0 "
                   "(default) disables tail retention")
    p.add_argument("--anomaly-config", metavar="FILE_OR_JSON",
                   default=None,
                   help="anomaly watchdog (inference/anomaly.py): a "
                   "JSON file path (or inline JSON object) tuning the "
                   "rule thresholds (SLO burn rate, TTFT/ITL EWMA "
                   "shift, cache hit-rate collapse, breaker flaps, "
                   "deadline/preemption spikes, host-gap regression, "
                   "wedged scheduler), hysteresis hold, and the "
                   "optional capture_iters/capture_dir auto "
                   "/debug/trace arm. {} enables every rule at "
                   "defaults")
    p.add_argument("--bundle-on-anomaly", action="store_true",
                   help="auto-capture a forensic debug bundle "
                   "(GET /debug/bundle schema) into a bounded ring "
                   "each time a watchdog rule fires (needs "
                   "--anomaly-config)")
    p.add_argument("--no-iteration-profile", action="store_true",
                   help="disable the iteration-phase profiler (on by "
                   "default: per-iteration sweep/admission/build/"
                   "device/commit/epilogue attribution in flight "
                   "records, cloud_server_iter_phase_ms histograms, "
                   "/stats iteration_profile, and the "
                   "GET /debug/scheduler_trace Perfetto export)")
    p.add_argument("--ngram-draft", action="store_true",
                   help="speculative decoding WITHOUT a draft model: "
                   "propose continuations of repeated n-grams from the "
                   "sequence so far (exact output; wins on repetitive "
                   "text); batch mode only")
    from cloud_server_tpu.models.lora import add_lora_args
    add_lora_args(p)
    return p


def load_params(model_cfg, checkpoint_dir: str | None, step: int | None,
                seed: int, loss_fn_module=None, mesh=None):
    """Params-only restore (no optimizer-moment IO), sharded onto `mesh`
    (default: single-device). Random-inits when no checkpoint_dir."""
    import jax

    from cloud_server_tpu.config import MeshConfig
    from cloud_server_tpu.models import transformer
    from cloud_server_tpu.parallel.mesh import make_mesh

    if loss_fn_module is None:
        loss_fn_module = transformer
    if checkpoint_dir is None:
        print("[generate] no --checkpoint-dir; using random init",
              file=sys.stderr)
        return loss_fn_module.init_params(model_cfg, jax.random.key(seed))

    from cloud_server_tpu.training.checkpoint import restore_params
    mesh = mesh if mesh is not None else make_mesh(MeshConfig())
    return restore_params(checkpoint_dir, model_cfg, mesh, step=step,
                          loss_fn_module=loss_fn_module)


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    from cloud_server_tpu.config import InferConfig, ModelConfig, from_json
    from cloud_server_tpu.data.tokenizer import get_tokenizer
    from cloud_server_tpu.inference.server import InferenceServer

    raw = {}
    if args.config:
        with open(args.config) as f:
            raw = json.load(f)
    hf_params = None
    if args.hf_checkpoint:
        if args.checkpoint_dir:
            raise SystemExit(
                "--hf-checkpoint and --checkpoint-dir are mutually "
                "exclusive")
        if args.step is not None:
            raise SystemExit("--step does not apply to --hf-checkpoint")
        from cloud_server_tpu.models.lora import lora_config_from_args
        if lora_config_from_args(args) is not None:
            raise SystemExit(
                "--lora-* flags do not apply to --hf-checkpoint (merge "
                "adapters into an HF checkpoint first, or train from a "
                "framework checkpoint)")
        from cloud_server_tpu.models.hf_convert import load_hf_checkpoint
        model_cfg, hf_params = load_hf_checkpoint(
            args.hf_checkpoint, **raw.get("model", {}))
    else:
        model_cfg = from_json(ModelConfig, raw.get("model", {}))
    if args.kv_cache_int8:
        model_cfg = dataclasses.replace(model_cfg, kv_cache_dtype="int8")
    if args.decode_impl is not None:
        if args.contiguous and args.decode_impl != "xla":
            raise SystemExit(
                "--decode-impl pallas needs the paged server; drop "
                "--contiguous")
        model_cfg = dataclasses.replace(
            model_cfg, decode_attention_impl=args.decode_impl)
    if args.spec_drafts and args.contiguous:
        raise SystemExit(
            "--spec-drafts is the paged server's in-server speculation; "
            "it cannot run with --contiguous (use --ngram-draft/"
            "--draft-config for the batch API instead)")
    if args.spec_drafts and args.ngram_draft:
        raise SystemExit(
            "--spec-drafts (in-server n-gram) and --ngram-draft (batch "
            "API) are mutually exclusive speculation paths")
    tok = get_tokenizer(args.tokenizer)
    if tok.vocab_size > model_cfg.vocab_size:
        raise SystemExit(
            f"tokenizer vocab ({tok.vocab_size}) exceeds model vocab "
            f"({model_cfg.vocab_size})")

    prompts = []
    for prm in args.prompt:
        if prm == "-":
            prompts.extend(line.rstrip("\n") for line in sys.stdin)
        else:
            prompts.append(prm)
    if not prompts and args.serve_http is None:
        raise SystemExit("no prompts (use --prompt, repeatable, or '-')")

    from cloud_server_tpu.models.lora import (
        export_merged, load_lora_config, lora_config_from_args,
        make_lora_module)
    lcfg = lora_config_from_args(args)
    if args.checkpoint_dir:
        saved = load_lora_config(args.checkpoint_dir)
        if saved is not None:
            # the sidecar written at training time is authoritative: a
            # mismatched alpha would silently rescale the adapters
            if lcfg is not None and lcfg != saved:
                raise SystemExit(
                    f"--lora-* flags {lcfg} contradict the checkpoint's "
                    f"recorded LoRA config {saved}; drop the flags (the "
                    "sidecar is used automatically)")
            lcfg = saved
    if args.ema:
        if hf_params is not None or lcfg is not None:
            raise SystemExit("--ema applies to framework checkpoints "
                             "without LoRA flags")
        if not args.checkpoint_dir:
            raise SystemExit("--ema needs --checkpoint-dir")
        from cloud_server_tpu.config import MeshConfig
        from cloud_server_tpu.models import transformer
        from cloud_server_tpu.parallel.mesh import make_mesh
        from cloud_server_tpu.training.checkpoint import restore_ema_params
        moe_module = None
        if model_cfg.num_experts >= 2:
            from cloud_server_tpu.models import moe as moe_module
        try:
            params = restore_ema_params(
                args.checkpoint_dir, model_cfg, make_mesh(MeshConfig()),
                step=args.step, loss_fn_module=moe_module or transformer)
        except FileNotFoundError as e:
            raise SystemExit(str(e))
    elif hf_params is not None:
        params = hf_params
    elif lcfg is not None:
        base_module = transformer
        if model_cfg.num_experts >= 2:
            from cloud_server_tpu.models import moe as base_module
        lora_module = make_lora_module(lcfg, base_module=base_module)
        params = load_params(model_cfg, args.checkpoint_dir, args.step,
                             args.seed, loss_fn_module=lora_module)
        params = export_merged(params, lcfg, base_module=base_module)
    else:
        moe_module = None
        if model_cfg.num_experts >= 2:
            from cloud_server_tpu.models import moe as moe_module
        params = load_params(model_cfg, args.checkpoint_dir, args.step,
                             args.seed, loss_fn_module=moe_module)
    if args.quantize:
        from cloud_server_tpu.models.quantization import quantize_params
        params = quantize_params(params)
    infer_cfg = InferConfig(
        max_decode_len=args.max_new, temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p,
        eos_token_id=tok.eos_id if tok.eos_id is not None else -1,
        pad_token_id=tok.pad_id or 0,
        trace_capacity=args.trace_capacity,
        trace_tail_capacity=args.trace_tail_capacity,
        bundle_on_anomaly=args.bundle_on_anomaly)

    def load_draft():
        """Draft model for in-server speculation (--draft-config with
        the paged server). Returns (params, cfg) or (None, None)."""
        if not args.draft_config or args.contiguous:
            return None, None
        with open(args.draft_config) as f:
            draft_cfg = from_json(ModelConfig, json.load(f).get("model", {}))
        draft_module = None
        if draft_cfg.num_experts >= 2:
            from cloud_server_tpu.models import moe as draft_module
        draft_params = load_params(draft_cfg, args.draft_checkpoint_dir,
                                   None, args.seed + 1,
                                   loss_fn_module=draft_module)
        return draft_params, draft_cfg

    def make_server(max_len: int, max_slots: int):
        """Build the serving backend: paged by default, contiguous on
        --contiguous. Same client API either way (submit / generate /
        start / stop)."""
        if args.contiguous:
            prefix_toks = (tok.encode(args.prefix,
                                      add_bos=args.add_bos
                                      and tok.bos_id is not None)
                           if args.prefix else None)
            return InferenceServer(
                params, model_cfg, infer_cfg, max_slots=max_slots,
                max_len=max_len, seed=args.seed,
                decode_chunk=args.decode_chunk,
                prefix_tokens=prefix_toks,
                qos=args.qos_config,
                slo=args.slo_config,
                tracing=args.trace_sample_rate or None,
                faults=args.fault_plan,
                anomaly=args.anomaly_config,
                overlap=False if args.no_overlap else None,
                iteration_profile=False if args.no_iteration_profile else None)
        if args.prefix:
            print("[generate] note: the paged server reuses shared "
                  "prefixes automatically (radix page cache); --prefix "
                  "needs no pre-registration — prompts that start with "
                  "the prefix text hit the cache after the first request",
                  file=sys.stderr)
        ps = args.page_size
        max_context = -(-max_len // ps) * ps  # round up to a page multiple
        prefill_chunk = -(-max(ps, args.prefill_chunk) // ps) * ps
        draft_params, draft_cfg = load_draft()
        spec = args.spec_drafts
        if draft_cfg is not None and spec == 0:
            spec = args.num_draft  # --draft-config implies speculation
        from cloud_server_tpu.inference.paged_server import (
            PagedInferenceServer)
        return PagedInferenceServer(
            params, model_cfg, infer_cfg, max_slots=max_slots,
            max_context=max_context, page_size=ps,
            num_pages=args.num_pages or None,
            decode_chunk=args.decode_chunk,
            spec_drafts=spec,
            spec_control=args.spec_control,
            prefill_chunk=prefill_chunk, seed=args.seed,
            allocation=args.allocation,
            scheduler=args.scheduler,
            overlap=False if args.no_overlap else None,
            mixed_token_budget=args.mixed_token_budget,
            flight_recorder_size=args.flight_recorder or None,
            draft_params=draft_params, draft_cfg=draft_cfg,
            qos=args.qos_config,
            slo=args.slo_config,
            tracing=args.trace_sample_rate or None,
            faults=args.fault_plan,
            brownout=args.brownout,
            anomaly=args.anomaly_config,
            iteration_profile=False if args.no_iteration_profile else None,
            tokenizer=tok)  # regex-constrained requests compile vs it

    if args.serve_http is not None:
        if args.ngram_draft or (args.draft_config and args.contiguous):
            raise SystemExit(
                "--ngram-draft is batch-mode only (the serving "
                "equivalent is --spec-drafts), and --draft-config "
                "serving needs the paged server (drop --contiguous)")
        from cloud_server_tpu.inference.http_server import HttpFrontend
        max_len = args.max_len or model_cfg.max_seq_len
        srv = make_server(max_len, args.max_slots).start()
        access_log = (True if args.access_log == "stderr"
                      else args.access_log)
        front = HttpFrontend(srv, tokenizer=tok, port=args.serve_http,
                             access_log=access_log)
        front.start()
        if args.profiler_port is not None:
            from cloud_server_tpu.utils.tracing import (
                start_profiler_server)
            start_profiler_server(args.profiler_port)
            print(f"[generate] jax profiler server on port "
                  f"{args.profiler_port}", file=sys.stderr)
        host, port = front.address
        print(f"[generate] serving on http://{host}:{port} — try:\n"
              f"  curl -N -s {host}:{port}/generate "
              "-d '{\"prompt\": \"hello\"}'", file=sys.stderr)
        try:
            import signal
            signal.pause()
        except (KeyboardInterrupt, AttributeError):
            pass
        finally:
            front.stop()
            srv.stop()
        return

    encoded = [tok.encode(p, add_bos=args.add_bos and tok.bos_id is not None)
               or [0] for p in prompts]
    if args.draft_config or args.ngram_draft:
        import jax
        import numpy as np

        from cloud_server_tpu.inference.speculative import (
            speculative_generate)
        if args.quantize:
            raise SystemExit("--quantize + speculative decoding not "
                             "supported yet")
        if args.draft_config and args.ngram_draft:
            raise SystemExit("--draft-config and --ngram-draft are "
                             "mutually exclusive draft sources")
        if args.prefix:
            raise SystemExit(
                "--prefix is a serving-path feature; the speculative "
                "batch path would silently ignore it")
        draft_cfg = draft_params = None
        if args.draft_config:
            with open(args.draft_config) as f:
                draft_cfg = from_json(ModelConfig,
                                      json.load(f).get("model", {}))
            draft_module = None
            if draft_cfg.num_experts >= 2:
                from cloud_server_tpu.models import moe as draft_module
            draft_params = load_params(draft_cfg, args.draft_checkpoint_dir,
                                       None, args.seed + 1,
                                       loss_fn_module=draft_module)
        longest = max(len(e) for e in encoded)
        # honour --max-len / the trained context window like the plain
        # path: the cache must hold prompt + new tokens + the speculative
        # window's overhang, so clamp max_new to what fits.
        cap = args.max_len or model_cfg.max_seq_len
        budget = cap - longest - args.num_draft - 1
        if budget < 1:
            raise SystemExit(
                f"prompt ({longest}) + speculative window "
                f"({args.num_draft + 1}) leaves no room to decode within "
                f"max_len={cap}; raise --max-len or shorten the prompt")
        max_new = min(args.max_new, budget)
        if max_new < args.max_new:
            print(f"[generate] clamping --max-new {args.max_new} -> "
                  f"{max_new} to fit max_len={cap}", file=sys.stderr)
            infer_cfg = dataclasses.replace(infer_cfg,
                                            max_decode_len=max_new)
        padded = np.zeros((len(encoded), longest), np.int32)
        lengths = np.asarray([len(e) for e in encoded], np.int32)
        for i, e in enumerate(encoded):
            padded[i, :len(e)] = e
        toks = speculative_generate(
            params, draft_params, jax.numpy.asarray(padded),
            jax.random.key(args.seed), cfg=model_cfg, draft_cfg=draft_cfg,
            infer_cfg=infer_cfg, num_draft=args.num_draft,
            max_len=longest + max_new + args.num_draft + 1,
            prompt_lengths=jax.numpy.asarray(lengths))
        for prompt, row in zip(prompts, np.asarray(toks)):
            row = list(row)
            if infer_cfg.eos_token_id >= 0 and infer_cfg.eos_token_id in row:
                row = row[:row.index(infer_cfg.eos_token_id)]
            # only TRAILING pads are padding; a mid-stream token that
            # happens to equal pad_token_id is real output (byte 0 for the
            # byte tokenizer) and the plain path prints it
            while row and row[-1] == infer_cfg.pad_token_id:
                row.pop()
            print(f"=== {prompt!r}")
            print(tok.decode(row))
        return

    longest = max(len(e) for e in encoded)
    max_len = args.max_len or min(model_cfg.max_seq_len,
                                  longest + args.max_new +
                                  (0 if args.contiguous
                                   else args.spec_drafts + 1))
    srv = make_server(max_len, min(args.max_slots, len(encoded)))
    outs = srv.generate(encoded, max_new_tokens=args.max_new)
    for prompt, out in zip(prompts, outs):
        print(f"=== {prompt!r}")
        print(tok.decode(out))


if __name__ == "__main__":
    main()

"""Token datasets for LM pretraining.

The on-disk format is the standard flat binary token stream (a single
dtype'd array of token ids, as produced by most tokenizer pipelines);
`MemmapTokenDataset` views it zero-copy via np.memmap and slices fixed
seq_len windows, so the host never holds more than the batches in flight.
The optional native C++ reader in `cloud_server_tpu.runtime` reads the
same format with O_DIRECT-style threaded prefetch; this module is the
always-available pure-numpy path.
"""

from __future__ import annotations

import os

import numpy as np


def write_token_file(path: str | os.PathLike, tokens: np.ndarray,
                     dtype=np.uint16) -> None:
    """Write a flat token array in the binary format the readers expect."""
    np.asarray(tokens, dtype=dtype).tofile(os.fspath(path))


class MemmapTokenDataset:
    """Fixed-window LM dataset over a flat binary token file.

    Example i is tokens[i*seq_len : (i+1)*seq_len]; windows are
    non-overlapping and the tail that doesn't fill one is dropped. The
    train loss shifts within the window (`next_token_loss` drops the last
    position), so windows stay exactly seq_len — which keeps S divisible
    for sp-sharded attention.
    """

    def __init__(self, path: str | os.PathLike, seq_len: int, dtype=None):
        """dtype None => auto-detect from the `<path>.meta.json` sidecar
        written by `tokenizer.prepare_corpus`, falling back to uint16."""
        self.path = os.fspath(path)
        self.seq_len = seq_len
        if dtype is None:
            dtype = np.uint16
            meta_path = self.path + ".meta.json"
            if os.path.exists(meta_path):
                import json
                with open(meta_path) as f:
                    dtype = np.dtype(json.load(f)["dtype"])
        self._tokens = np.memmap(self.path, dtype=dtype, mode="r")
        n = len(self._tokens) // seq_len
        if n <= 0:
            raise ValueError(
                f"{self.path}: {len(self._tokens)} tokens < seq_len "
                f"({seq_len}); no full window fits")
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        if not 0 <= i < self._n:
            raise IndexError(i)
        s = i * self.seq_len
        return {"tokens": np.asarray(self._tokens[s:s + self.seq_len],
                                     np.int32)}


class MixtureDataset:
    """Deterministic weighted mixture over several map-style datasets.

    Pretraining corpora are usually a weighted blend (web + code + books,
    ...). Example i draws its source from a hash-seeded categorical over
    `weights` and then a uniformly random example index WITH REPLACEMENT
    within that source — both pure functions of (seed, i), so the mixture
    composes with the resumable sharded sampler exactly like a plain
    dataset: restoring a step replays the identical blend. I.i.d.
    sampling means there is no per-source epoch traversal or coverage
    guarantee (a pass over len(self) indices repeats some examples and
    misses others — the standard choice for weighted pretraining blends,
    where small high-weight corpora must repeat anyway); the default
    length is just the unweighted example count across sources, a
    bookkeeping convention for "one nominal epoch".
    """

    def __init__(self, datasets, weights, *, num_examples: int | None = None,
                 seed: int = 0):
        if len(datasets) != len(weights) or not datasets:
            raise ValueError("need equally many datasets and weights (>=1)")
        w = np.asarray(weights, np.float64)
        if (w <= 0).any():
            raise ValueError(f"weights must be positive, got {weights}")
        self._datasets = list(datasets)
        self._cum = np.cumsum(w / w.sum())
        for k, d in enumerate(datasets):
            if d.seq_len != datasets[0].seq_len:
                raise ValueError(
                    f"all mixture sources must share seq_len: source {k} "
                    f"has seq_len={d.seq_len} != {datasets[0].seq_len} "
                    "(source 0) — retokenize or drop the mismatched file")
        self.seq_len = datasets[0].seq_len
        self.seed = seed
        if num_examples is not None and num_examples <= 0:
            raise ValueError(f"num_examples must be > 0, got {num_examples}")
        # default "nominal epoch" length: the unweighted example count
        self._n = (num_examples if num_examples is not None
                   else int(sum(len(d) for d in self._datasets)))

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        if not 0 <= i < self._n:
            raise IndexError(i)
        rng = np.random.default_rng((self.seed, 0x6D69, i))
        src = int(np.searchsorted(self._cum, rng.random(), side="right"))
        src = min(src, len(self._datasets) - 1)
        ds = self._datasets[src]
        return ds[int(rng.integers(0, len(ds)))]


class SyntheticLMDataset:
    """Deterministic random tokens — for tests and benches (no disk IO)."""

    def __init__(self, num_examples: int, seq_len: int, vocab_size: int,
                 seed: int = 0):
        self._n = num_examples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        if not 0 <= i < self._n:
            raise IndexError(i)
        rng = np.random.default_rng((self.seed, i))
        return {"tokens": rng.integers(
            0, self.vocab_size, self.seq_len, dtype=np.int32)}

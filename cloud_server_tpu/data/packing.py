"""Sequence packing: multiple documents per training row, separated by
segment ids, so short examples don't burn compute as padding.

Packed rows pair a `tokens` row with a same-shape `segment_ids` row:
0 marks padding, documents count 1, 2, ... within each row. Downstream,
the dense transformer uses the ids three ways (all derived, no extra
inputs): attention is masked to same-segment pairs (block-diagonal
causal), RoPE positions restart at each segment start, and the loss masks
targets that would cross a boundary (the last token of one document must
not be trained to predict the first token of the next). The result is
numerically identical to running each document alone — tested in
tests/test_packing.py — while keeping every (B, S) shape static.

Packing is greedy in arrival order: documents are appended to the current
row while they fit; a document longer than seq_len is split into
seq_len-sized pieces, each becoming its own segment (positions restart
per piece — the price of keeping shapes static; shuffle-robust training
is insensitive to this).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


def pack_documents(docs: Iterable[Sequence[int]], seq_len: int,
                   *, pad_id: int = 0
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Pack documents into rows.

    Returns (tokens, segment_ids), both (N, seq_len) int32; segment ids
    are 1-based per row, 0 marks padding.
    """
    rows_t: list[np.ndarray] = []
    rows_s: list[np.ndarray] = []
    cur_t: list[int] = []
    cur_s: list[int] = []
    seg = 0

    def flush():
        nonlocal cur_t, cur_s, seg
        if not cur_t:
            return
        pad = seq_len - len(cur_t)
        rows_t.append(np.asarray(cur_t + [pad_id] * pad, np.int32))
        rows_s.append(np.asarray(cur_s + [0] * pad, np.int32))
        cur_t, cur_s, seg = [], [], 0

    for doc in docs:
        doc = list(doc)
        if not doc:
            continue
        for start in range(0, len(doc), seq_len):
            piece = doc[start:start + seq_len]
            if len(cur_t) + len(piece) > seq_len:
                flush()
            seg += 1
            cur_t.extend(piece)
            cur_s.extend([seg] * len(piece))
    flush()
    if not rows_t:
        return (np.zeros((0, seq_len), np.int32),
                np.zeros((0, seq_len), np.int32))
    return np.stack(rows_t), np.stack(rows_s)


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of positions holding real tokens (1.0 = no padding)."""
    if segment_ids.size == 0:
        return 1.0
    return float((segment_ids != 0).mean())


class PackedTokenDataset:
    """In-memory packed dataset: {"tokens", "segment_ids"} per example.

    For corpus-scale data, pack offline and memmap the two arrays; this
    class is the reference implementation and the fine-tuning-scale path.
    """

    def __init__(self, docs: Iterable[Sequence[int]], seq_len: int,
                 *, pad_id: int = 0):
        self.tokens, self.segment_ids = pack_documents(
            docs, seq_len, pad_id=pad_id)
        self.seq_len = seq_len

    def __len__(self) -> int:
        return self.tokens.shape[0]

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        return {"tokens": self.tokens[i],
                "segment_ids": self.segment_ids[i]}

"""Host data pipeline: deterministic sharded sampling, collation, and
double-buffered device prefetch producing *global* sharded arrays.

Multi-host model: every process runs an identical `ShardedSampler` (same
seed ⇒ same per-epoch permutation), takes its own contiguous slice of each
global batch, and `jax.make_array_from_process_local_data` assembles the
logical global array from the per-process shards — the standard JAX
multi-host input recipe (no process ever holds the full global batch).
On a single process this degrades to a plain sharded device_put.

Resume: the sampler's state is (epoch, batch_in_epoch) — two ints saved
next to the model checkpoint — and `load_state_dict` fast-forwards
without touching the data, so a resumed run sees the exact same stream.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any, Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding


class ShardedSampler:
    """Deterministic, resumable index sampler sharded across processes.

    Each epoch draws a fresh permutation from (seed, epoch); each global
    step takes `global_batch_size` indices and this process keeps its
    `local_batch_size` slice. Incomplete trailing batches are dropped so
    shapes stay static for jit.
    """

    def __init__(self, num_examples: int, global_batch_size: int, *,
                 seed: int = 0, shuffle: bool = True,
                 process_index: int | None = None,
                 process_count: int | None = None):
        self.num_examples = num_examples
        self.global_batch_size = global_batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        if global_batch_size % self.process_count:
            raise ValueError(
                f"global_batch_size {global_batch_size} not divisible by "
                f"process_count {self.process_count}")
        self.local_batch_size = global_batch_size // self.process_count
        self.batches_per_epoch = num_examples // global_batch_size
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"dataset of {num_examples} examples can't fill one global "
                f"batch of {global_batch_size}")
        self.epoch = 0
        self.batch_in_epoch = 0

    def _perm(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.num_examples)
        return np.random.default_rng(
            (self.seed, epoch)).permutation(self.num_examples)

    def __iter__(self) -> Iterator[np.ndarray]:
        """Yield this process's index slice for each global batch, forever
        (epochs advance automatically)."""
        while True:
            perm = self._perm(self.epoch)
            while self.batch_in_epoch < self.batches_per_epoch:
                g0 = self.batch_in_epoch * self.global_batch_size
                local = perm[g0 + self.process_index * self.local_batch_size:
                             g0 + (self.process_index + 1) * self.local_batch_size]
                self.batch_in_epoch += 1
                yield local
            self.epoch += 1
            self.batch_in_epoch = 0

    def state_dict(self) -> dict[str, int]:
        return {"epoch": self.epoch, "batch_in_epoch": self.batch_in_epoch}

    def load_state_dict(self, state: dict[str, int]) -> None:
        self.epoch = int(state["epoch"])
        self.batch_in_epoch = int(state["batch_in_epoch"])


def _collate(dataset, indices: np.ndarray) -> dict[str, np.ndarray]:
    if hasattr(dataset, "read_batch"):  # native gathered read (runtime/)
        return dataset.read_batch(indices)
    examples = [dataset[int(i)] for i in indices]
    return {k: np.stack([e[k] for e in examples]) for k in examples[0]}


def make_global_batch(local: dict[str, np.ndarray],
                      sharding: NamedSharding) -> dict[str, jax.Array]:
    """Assemble per-process local shards into global sharded jax.Arrays."""
    return {k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in local.items()}


def prefetch_to_device(it: Iterator[Any], size: int = 2) -> Iterator[Any]:
    """Overlap host-side batch production with device compute.

    A daemon thread runs the upstream iterator (dataset reads, collation,
    device_put all happen there); the consumer pops ready batches from a
    bounded queue. Device transfer is async in JAX, so by the time the
    train step wants batch N+1 its copy has already been issued.
    """
    q: queue_mod.Queue = queue_mod.Queue(maxsize=size)
    _END = object()

    def producer():
        try:
            for item in it:
                q.put(item)
        finally:
            q.put(_END)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _END:
            return
        yield item


class DataLoader:
    """dataset + sampler + collate + global-array assembly + prefetch.

    Yields {"tokens": (global_B, S) jax.Array laid out as `sharding`}.
    Iterate it forever (epochs advance inside the sampler); pair
    `state_dict`/`load_state_dict` with the model checkpoint for exact
    data-stream resume.
    """

    def __init__(self, dataset, global_batch_size: int,
                 sharding: NamedSharding, *, seed: int = 0,
                 shuffle: bool = True, prefetch: int = 2,
                 process_index: int | None = None,
                 process_count: int | None = None):
        self.dataset = dataset
        self.sharding = sharding
        self.prefetch = prefetch
        self.sampler = ShardedSampler(
            len(dataset), global_batch_size, seed=seed, shuffle=shuffle,
            process_index=process_index, process_count=process_count)

    def _produce(self) -> Iterator[dict[str, jax.Array]]:
        for indices in self.sampler:
            yield make_global_batch(_collate(self.dataset, indices),
                                    self.sharding)

    def __iter__(self) -> Iterator[dict[str, jax.Array]]:
        if self.prefetch > 0:
            return prefetch_to_device(self._produce(), self.prefetch)
        return self._produce()

    def state_dict(self) -> dict[str, int]:
        return self.sampler.state_dict()

    def load_state_dict(self, state: dict[str, int]) -> None:
        self.sampler.load_state_dict(state)

"""Tokenizers: byte-level fallback + optional HuggingFace-backed wrapper,
and corpus preparation into the flat binary token format.

The framework's data path consumes flat binary token files
(`data/dataset.py`); this module produces them from raw text. Two
implementations behind one small interface (`encode`/`decode`/
`vocab_size`/`bos_id`/`eos_id`/`pad_id`):

* `ByteTokenizer` — zero-dependency, always available: ids 0..255 are raw
  bytes, then BOS/EOS/PAD specials. Lossless on arbitrary UTF-8.
* `HFTokenizer` — wraps a `tokenizers`/`transformers` fast tokenizer
  loaded from a LOCAL file or directory (no hub download — serving
  environments are assumed egress-free). Import is lazy and failure is a
  clear error, not an import-time crash.

`prepare_corpus` streams text → tokens → .bin in bounded memory, choosing
uint16/uint32 by vocab size to match `MemmapTokenDataset`'s dtype knob.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, Sequence

import numpy as np


class ByteTokenizer:
    """Lossless byte-level tokenizer: 0..255 bytes + BOS/EOS/PAD."""

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def encode(self, text: str, *, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids.insert(0, self.bos_id)
        if add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """A local HuggingFace fast tokenizer behind the framework interface.

    `path` is a local `tokenizer.json` file or a directory containing one
    (a saved `PreTrainedTokenizerFast`); nothing is fetched remotely.
    """

    def __init__(self, path: str | os.PathLike):
        try:
            from tokenizers import Tokenizer
        except ImportError as e:  # pragma: no cover
            raise RuntimeError(
                "HFTokenizer needs the `tokenizers` package; use "
                "ByteTokenizer where it is unavailable") from e
        path = os.fspath(path)
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path}: no local tokenizer.json (remote hub loading is "
                "deliberately unsupported — this environment has no egress)")
        self._tok = Tokenizer.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()

        def _tid(*names):
            for n in names:
                t = self._tok.token_to_id(n)
                if t is not None:
                    return t
            return None

        self.bos_id = _tid("<s>", "<bos>", "<|begin_of_text|>", "[CLS]")
        self.eos_id = _tid("</s>", "<eos>", "<|end_of_text|>",
                           "<|endoftext|>", "[SEP]")
        self.pad_id = _tid("<pad>", "[PAD]")
        # provenance matters downstream: grammar.token_bytes bans
        # DECLARED specials only — a fallback pad (eos, else 0) must not
        # make a real vocab id unspellable under a constraint
        self.pad_is_declared = self.pad_id is not None
        if self.pad_id is None:  # fall back to EOS, the common convention
            self.pad_id = self.eos_id if self.eos_id is not None else 0

    def encode(self, text: str, *, add_bos: bool = False,
               add_eos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        if add_bos and self.bos_id is not None:
            ids.insert(0, self.bos_id)
        if add_eos and self.eos_id is not None:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def get_tokenizer(spec: str | os.PathLike = "byte"):
    """"byte" -> ByteTokenizer; anything else is a local HF tokenizer path."""
    if os.fspath(spec) == "byte":
        return ByteTokenizer()
    return HFTokenizer(spec)


def token_dtype(vocab_size: int) -> np.dtype:
    return np.dtype(np.uint16 if vocab_size <= 0xFFFF else np.uint32)


def _iter_chunks(path: str | os.PathLike,
                 chunk_bytes: int) -> Iterator[str]:
    """Stream a UTF-8 text file in chunks, preferring line boundaries (so
    tokenizers with merges spanning a boundary only ever lose cross-LINE
    merges, which none of the supported formats have). A newline-free
    stretch longer than 4×chunk_bytes is flushed mid-line anyway — the
    bounded-memory contract beats boundary purity on single-line corpora."""
    with open(path, encoding="utf-8") as f:
        buf = ""
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                if buf:
                    yield buf
                return
            buf += chunk
            cut = buf.rfind("\n") + 1
            if cut:
                yield buf[:cut]
                buf = buf[cut:]
            elif len(buf) >= 4 * chunk_bytes:
                yield buf
                buf = ""


def prepare_corpus(text_path: str | os.PathLike,
                   out_path: str | os.PathLike, tokenizer=None, *,
                   append_eos_per_chunk: bool = False,
                   chunk_bytes: int = 1 << 20) -> int:
    """Tokenize a text file into the flat binary format; returns #tokens.

    Streams in `chunk_bytes` pieces so corpora never need to fit in
    memory. The output dtype follows the tokenizer's vocab size and is
    what `MemmapTokenDataset(path, seq_len, dtype=...)` expects.
    """
    tokenizer = tokenizer or ByteTokenizer()
    dtype = token_dtype(tokenizer.vocab_size)
    total = 0
    with open(out_path, "wb") as out:
        for text in _iter_chunks(text_path, chunk_bytes):
            ids = tokenizer.encode(text, add_eos=append_eos_per_chunk)
            np.asarray(ids, dtype).tofile(out)
            total += len(ids)
    # Sidecar metadata: the flat format itself carries no dtype, and a
    # uint32 file silently read as uint16 is garbage — consumers
    # (MemmapTokenDataset) auto-detect from this when present.
    with open(f"{os.fspath(out_path)}.meta.json", "w") as f:
        json.dump({"dtype": dtype.name, "vocab_size": tokenizer.vocab_size,
                   "num_tokens": total}, f)
    return total


def main(argv: Iterable[str] | None = None) -> None:
    """CLI: `python -m cloud_server_tpu.data.tokenizer in.txt out.bin`."""
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m cloud_server_tpu.data.tokenizer",
        description="Tokenize a text file into a flat binary token file.")
    p.add_argument("text", help="input UTF-8 text file")
    p.add_argument("out", help="output .bin token file")
    p.add_argument("--tokenizer", default="byte",
                   help='"byte" or a local tokenizer.json path')
    args = p.parse_args(argv)
    tok = get_tokenizer(args.tokenizer)
    n = prepare_corpus(args.text, args.out, tok)
    print(f"{args.out}: {n} tokens "
          f"(vocab {tok.vocab_size}, dtype {token_dtype(tok.vocab_size)})")


if __name__ == "__main__":
    main()

from cloud_server_tpu.data.dataset import (  # noqa: F401
    MemmapTokenDataset,
    SyntheticLMDataset,
    write_token_file,
)
from cloud_server_tpu.data.loader import (  # noqa: F401
    DataLoader,
    ShardedSampler,
    prefetch_to_device,
)

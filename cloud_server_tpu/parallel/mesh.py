"""Device mesh construction.

One canonical mesh for the whole framework, axes (dp, pp, fsdp, ep, sp, tp)
— see `MeshConfig`. On a real pod slice, `mesh_utils.create_device_mesh`
lays the logical mesh onto the physical ICI torus so the innermost axes
(tp, sp) get the shortest links; across slices/hosts the outer axes (dp, pp)
ride DCN. On CPU (tests / dry-run with --xla_force_host_platform_device_count)
we fall back to a plain reshape of the device list.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from cloud_server_tpu.config import MeshConfig


_CURRENT_MESH: Mesh | None = None


def set_current_mesh(mesh: Mesh | None) -> Mesh | None:
    """Register the process-wide mesh (None clears it). Model code that
    needs mesh context outside an explicit shard_map (e.g.
    attention_impl="ring") reads it via `current_mesh()`."""
    global _CURRENT_MESH
    _CURRENT_MESH = mesh
    return mesh


def current_mesh() -> Mesh:
    if _CURRENT_MESH is None:
        raise RuntimeError(
            "no mesh registered — build one with make_mesh() (it registers "
            "itself) or call set_current_mesh()")
    return _CURRENT_MESH


def maybe_current_mesh() -> Mesh | None:
    """current_mesh() for callers that degrade gracefully without one
    (e.g. activation sharding anchors in model code)."""
    return _CURRENT_MESH


def make_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Build a named Mesh with canonical axis order from a MeshConfig.

    Axis sizes must multiply to the number of devices used. Axes of size 1
    are kept in the mesh (they are free) so sharding specs never need to
    special-case a missing axis.
    """
    if devices is None:
        devices = jax.devices()
    n = cfg.num_devices
    if n > len(devices):
        raise ValueError(
            f"MeshConfig wants {n} devices but only {len(devices)} available"
        )
    devices = devices[:n]
    shape = tuple(cfg.axis_sizes()[a] for a in MeshConfig.AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return set_current_mesh(Mesh(dev_array, MeshConfig.AXIS_ORDER))


def mesh_for_devices(n_devices: int, *, tp: int = 1, sp: int = 1, pp: int = 1,
                     ep: int = 1, dp: int = 1) -> Mesh:
    """Convenience: put every explicitly-requested axis in place and absorb
    the remaining device count into fsdp."""
    used = tp * sp * pp * ep * dp
    if n_devices % used != 0:
        raise ValueError(f"{n_devices} devices not divisible by {used}")
    cfg = MeshConfig(dp=dp, pp=pp, fsdp=n_devices // used, ep=ep, sp=sp, tp=tp)
    return make_mesh(cfg)

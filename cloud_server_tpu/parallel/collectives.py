"""Collective-communication layer.

The framework's "communication backend" is XLA's collective set over
ICI/DCN. Inside `jit` with sharded arrays, XLA inserts these automatically
from sharding constraints; inside `shard_map` (ring attention, expert
all-to-all, pipeline transfers) we call them explicitly. These wrappers are
thin on purpose — they exist so the rest of the codebase names collectives
in one place, and so a future pallas DMA-based implementation can swap in
underneath without touching call sites.
"""

from __future__ import annotations

import jax
from jax import lax


def psum(x, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, tiled_axis: int = 0):
    """Gather shards along a mesh axis into a full array (concatenated on
    `tiled_axis`)."""
    return lax.all_gather(x, axis_name=axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=scatter_axis,
                            tiled=True)


def ppermute_shift(x, axis: str, shift: int = 1):
    """Rotate shards around a mesh axis (the ring step of ring attention).

    shift=+1 sends this device's value to the next device on the ring.
    """
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """MoE dispatch/combine primitive over the ep axis."""
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def pvary(x, axes):
    """Mark `x` as varying over `axes` (vma promotion for check_vma).

    jax.lax.pvary is deprecated in favour of lax.pcast(..., to="varying");
    this shim keeps one call site to track the API. No-op for empty axes.
    """
    axes = tuple(axes)
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)


def ring_exchange(chunks, axis: str, *, shift: int = 1):
    """Rotate every leaf of a pytree one hop around the ring — the k/v
    rotation step of ring attention and the stage handoff of the pipeline.
    A single named entry point so a DCN-aware or pallas-DMA implementation
    can replace the hop without touching the algorithms."""
    return jax.tree.map(lambda x: ppermute_shift(x, axis, shift), chunks)

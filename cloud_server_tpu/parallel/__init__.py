from cloud_server_tpu.parallel.distributed import (  # noqa: F401
    broadcast_from_primary,
    global_mesh_config,
    initialize,
    is_primary,
    make_hybrid_mesh,
    sync_global_devices,
)
from cloud_server_tpu.parallel.mesh import make_mesh  # noqa: F401
from cloud_server_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    DEFAULT_RULES,
    logical_to_sharding,
)

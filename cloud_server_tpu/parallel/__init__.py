from cloud_server_tpu.parallel.mesh import make_mesh  # noqa: F401
from cloud_server_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    DEFAULT_RULES,
    logical_to_sharding,
)

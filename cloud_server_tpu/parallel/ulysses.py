"""Ulysses (all-to-all) sequence/context parallelism over the `sp` axis.

The second sequence-parallel scheme next to ring attention
(`parallel/ring_attention.py`), selected with
`ModelConfig(attention_impl="ulysses")`. Where ring attention keeps queries
sequence-sharded and rotates kv chunks around the ring (n-1 ppermute hops,
comm proportional to kv size * (n-1)), Ulysses re-shards: one all-to-all
turns the sequence sharding into a *head* sharding, every device then runs
ordinary dense causal attention over the FULL sequence for H/sp of the
heads, and a second all-to-all restores the sequence sharding. Two
collectives total, each moving S*H*Dh/sp per device — cheaper than the
ring when sp is small relative to heads and S is moderate; the ring wins
when S is huge (its live buffers stay S/sp-sized, Ulysses materialises the
full S locally) or when sp exceeds the head count.

All-to-all layout: with local q of shape (B, S/sp, H, Dh), splitting the
head axis into sp chunks and concatenating received pieces along the
sequence axis yields (B, S, H/sp, Dh); device i ends up with head-chunk i
of every sequence chunk, in ring order, so the concatenated sequence is in
global order and causal masking needs no position bookkeeping. The inverse
all-to-all (split sequence, concat heads) restores the original layout.

GQA: when sp divides the local kv-head count, k/v ride the same all-to-all
(head-chunk boundaries then align with kv-group boundaries, since
H_loc/sp = (KH_loc/sp) * q_per_kv). Otherwise kv heads are first repeated
up to the q-head layout (MHA expansion) so chunks align trivially — the
comm-optimal choice for KH_loc < sp anyway, where some replication is
unavoidable.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cloud_server_tpu.ops.attention import causal_attention
from cloud_server_tpu.parallel import collectives


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      segment_ids: jnp.ndarray | None = None,
                      *, axis_name: str = "sp", scale: float | None = None):
    """Causal GQA over a sequence sharded on `axis_name`. Call under shard_map.

    q: (B, Sq_local, H, Dh); k, v: (B, Skv_local, KH, Dh) — the local
    chunks, in ring order (device i holds positions
    [i * Sq_local, (i+1) * Sq_local)). segment_ids: optional
    (B, Sq_local) packed ids sharded like the tokens — after the
    all-to-all every device attends over the FULL sequence, so the ids
    are all-gathered (B*S ints — negligible next to the kv all-to-all)
    and applied as the block-diagonal packed mask. Returns
    (B, Sq_local, H, Dh).
    """
    sp = lax.axis_size(axis_name)
    if sp == 1:
        return causal_attention(q, k, v, scale=scale,
                                segment_ids=segment_ids)
    h, kh = q.shape[2], k.shape[2]
    if h % sp:
        raise ValueError(
            f"ulysses attention needs local head count divisible by the "
            f"sp axis: heads={h}, sp={sp}")

    if kh % sp:
        # MHA expansion: repeat kv head j into q heads [j*g, (j+1)*g) so
        # head chunks align with q's after the all-to-all.
        g = h // kh
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)

    # sequence-sharded -> head-sharded: (B, S/sp, H, Dh) -> (B, S, H/sp, Dh)
    to_heads = functools.partial(collectives.all_to_all, axis=axis_name,
                                 split_axis=2, concat_axis=1)
    q_full, k_full, v_full = to_heads(q), to_heads(k), to_heads(v)

    seg_full = (None if segment_ids is None
                else collectives.all_gather(segment_ids, axis_name,
                                            tiled_axis=1))
    out = causal_attention(q_full, k_full, v_full, scale=scale,
                           segment_ids=seg_full)

    # head-sharded -> sequence-sharded: (B, S, H/sp, Dh) -> (B, S/sp, H, Dh)
    return collectives.all_to_all(out, axis=axis_name,
                                  split_axis=1, concat_axis=2)


def ulysses_attention_sharded(q, k, v, mesh, *, segment_ids=None,
                              scale=None, batch_axes=("dp", "fsdp"),
                              seq_axis="sp", head_axis="tp"):
    """shard_map wrapper: full (B, S, H, Dh) arrays in, Ulysses attention
    over the sp axis, full arrays out (still sharded by the same specs).
    Drop-in alternative to `ring_attention_sharded`; segment_ids (B, S)
    shard over the sequence like the tokens."""
    qspec = P(batch_axes, seq_axis, head_axis, None)
    fn = functools.partial(ulysses_attention, axis_name=seq_axis, scale=scale)
    if segment_ids is None:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
            check_vma=True)(q, k, v)
    sspec = P(batch_axes, seq_axis)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(qspec, qspec, qspec, sspec),
        out_specs=qspec, check_vma=True)(q, k, v, segment_ids)

"""Multi-host distributed runtime: initialization, hybrid ICI×DCN meshes,
and cross-host coordination helpers.

The comm backend is XLA itself: collectives are derived from sharding
annotations and ride ICI within a slice and DCN across slices — there is
no hand-written NCCL/MPI layer to manage. What this module adds is the
*process* plumbing around that:

* `initialize()` — one idempotent entry point over
  `jax.distributed.initialize`. On TPU pods the coordinator/process
  topology is autodetected from the environment; explicit args are for
  CPU/GPU clusters and tests.
* `make_hybrid_mesh(ici, dcn)` — a mesh whose DCN-crossing axes are the
  *outer* mesh dims (`mesh_utils.create_hybrid_device_mesh`), so the
  cheap/chatty collectives (tp/sp psums) stay on ICI and only dp/pp
  gradient reductions cross the data-center network. On hardware without
  slice metadata (CPU tests) it falls back to process-granule grouping,
  preserving the axis semantics.
* `is_primary()` / `sync_global_devices()` / `broadcast_from_primary()` —
  the small coordination vocabulary train loops and checkpointers need
  (process-0-only logging and saving already use these conventions).
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.experimental import mesh_utils, multihost_utils
from jax.sharding import Mesh

from cloud_server_tpu.config import MeshConfig
from cloud_server_tpu.parallel.mesh import set_current_mesh

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_ids=None) -> None:
    """Idempotent `jax.distributed.initialize`.

    On TPU pods call with no args (topology comes from the TPU runtime
    env). A second call is a no-op rather than an error, so library code
    can call it defensively.
    """
    global _initialized
    # NOTE: must not touch any backend-initialising JAX API here
    # (jax.process_count() etc.) — jax.distributed.initialize() has to run
    # before the XLA backend comes up.
    if _initialized or jax.distributed.is_initialized():
        _initialized = True
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def num_slices(devices=None) -> int:
    """Number of ICI-connected slices (1 on a single slice / CPU)."""
    devices = devices if devices is not None else jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) or 0 for d in devices}
    return len(slice_ids)


def make_hybrid_mesh(ici: MeshConfig, dcn: MeshConfig,
                     devices=None) -> Mesh:
    """Mesh over multiple slices: per-slice axis sizes from `ici`, across-
    slice sizes from `dcn` (their elementwise product is the global mesh).

    Keep `dcn` to the outer axes (dp, pp) — DCN bandwidth is orders of
    magnitude below ICI, and only per-step gradient/pipeline transfers
    tolerate it. The global axis size seen by sharding rules is
    ici.axis × dcn.axis.
    """
    devices = devices if devices is not None else jax.devices()
    for axis in ("fsdp", "ep", "sp", "tp"):
        if getattr(dcn, axis) > 1:
            raise ValueError(
                f"dcn mesh axis {axis!r} > 1: fsdp/ep/sp/tp collectives are "
                "per-layer and would serialise on DCN; keep DCN to dp/pp")
    n = ici.num_devices * dcn.num_devices
    if n != len(devices):
        raise ValueError(
            f"hybrid mesh wants {ici.num_devices}×{dcn.num_devices}={n} "
            f"devices, got {len(devices)}")
    ici_shape = tuple(ici.axis_sizes()[a] for a in MeshConfig.AXIS_ORDER)
    dcn_shape = tuple(dcn.axis_sizes()[a] for a in MeshConfig.AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    except ValueError:
        # no slice_index attribute (CPU tests, single-slice hardware):
        # group by process instead; same axis semantics, host = granule
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                process_is_granule=True)
        except ValueError:
            # single-process CPU fallback: plain reshape keeps the global
            # shape correct (no physical locality to optimise anyway)
            shape = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
            dev_array = np.asarray(devices).reshape(shape)
    return set_current_mesh(Mesh(dev_array, MeshConfig.AXIS_ORDER))


def global_mesh_config(ici: MeshConfig, dcn: MeshConfig) -> MeshConfig:
    """The MeshConfig equivalent of a hybrid mesh's global shape (what
    batch-size divisibility checks should be run against)."""
    sizes = {a: ici.axis_sizes()[a] * dcn.axis_sizes()[a]
             for a in MeshConfig.AXIS_ORDER}
    return MeshConfig(**sizes)


# -- coordination helpers ----------------------------------------------------

def is_primary() -> bool:
    return jax.process_index() == 0


def sync_global_devices(name: str) -> None:
    """Barrier across all hosts (no-op single-process)."""
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


def broadcast_from_primary(pytree):
    """Make process 0's host values authoritative everywhere (e.g. an RNG
    seed read from a file, a resolved checkpoint step)."""
    if jax.process_count() <= 1:
        return pytree
    return multihost_utils.broadcast_one_to_all(pytree)


def process_env_summary() -> dict:
    """Debug snapshot for launch scripts / failure reports."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "num_slices": num_slices(),
        "coordinator": os.environ.get("JAX_COORDINATOR_ADDRESS"),
    }

"""Ring attention — causal sequence/context parallelism over the `sp` axis.

Each device holds one contiguous chunk of the sequence (queries AND kv). The
kv chunks rotate around the ring via `ppermute` while every device folds the
visiting chunk into a running online-softmax accumulator (m, l, acc), so the
full S x S attention is computed with S/n-sized live buffers and n-1 ICI
hops. Communication overlaps compute under XLA's async collectives since
the ppermute of step t+1 has no data dependency on the math of step t.

Causality across chunks is handled with absolute positions: every chunk
carries its origin index, so a visiting chunk that is entirely in this
device's future contributes nothing (fully masked rows are explicitly
zeroed — no NaNs from -inf softmax).

Used inside `shard_map` (see `ring_attention_sharded`), or composed into
the transformer via ModelConfig(attention_impl="ring").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from cloud_server_tpu.parallel import collectives

NEG_INF = -1e30


def _chunk_merge(carry, q, k, v, q_off, kv_off, scale, seg_q=None,
                 seg_kv=None):
    """Fold one visiting kv chunk into the online-softmax accumulators.

    carry: (acc (B,KH,G,Sq,Dh) f32, m (B,KH,G,Sq,1) f32, l same).
    q: (B, Sq, H, Dh); k, v: (B, Skv, KH, Dh).
    q_off / kv_off: absolute position of element 0 of each chunk (traced).
    seg_q / seg_kv: optional (B, Sq) / (B, Skv) packed-segment ids — the
    visiting chunk's ids rotate around the ring with it, so cross-chunk
    attention is additionally masked to same-segment pairs.
    """
    acc, m, l = carry
    b, sq, h, dh = q.shape
    _, skv, kh, _ = k.shape
    g = h // kh

    qg = q.reshape(b, sq, kh, g, dh)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                   preferred_element_type=jnp.float32) * scale

    q_pos = q_off + jnp.arange(sq)
    kv_pos = kv_off + jnp.arange(skv)
    mask = (q_pos[:, None] >= kv_pos[None, :])[None, None, None]  # (1,1,1,Sq,Skv)
    if seg_q is not None:
        same = (seg_q[:, :, None] == seg_kv[:, None, :])  # (B, Sq, Skv)
        mask = jnp.logical_and(mask, same[:, None, None])
    s = jnp.where(mask, s, NEG_INF)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # Explicitly zero masked entries: when a whole row is masked,
    # exp(s - m_new) would be exp(0) = 1, not 0.
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.einsum(
        "bkgqs,bskd->bkgqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   segment_ids: jnp.ndarray | None = None,
                   *, axis_name: str = "sp", scale: float | None = None):
    """Causal GQA over a sequence sharded on `axis_name`. Call under shard_map.

    q: (B, Sq_local, H, Dh); k, v: (B, Skv_local, KH, Dh) — the local chunks.
    Chunks are assumed laid out in ring order: device i holds positions
    [i * Sq_local, (i+1) * Sq_local).

    segment_ids: optional (B, Sq_local) packed-sequence ids, sharded over
    the sequence exactly like the tokens. The kv chunk's ids rotate with
    it, so the block-diagonal packed mask is exact across chunk
    boundaries.

    Returns the local output chunk (B, Sq_local, H, Dh).
    """
    b, sq, h, dh = q.shape
    _, skv, kh, _ = k.shape
    g = h // kh
    if scale is None:
        scale = dh**-0.5

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    q_off = idx * sq
    has_seg = segment_ids is not None

    # Fresh accumulators are unvarying; inside shard_map they must carry
    # the same varying-manual-axes (vma) set as the chunks they accumulate,
    # or check_vma=True (the collective sanitizer mode) rejects the scan.
    vma = tuple(jax.typeof(q).vma)
    acc = collectives.pvary(jnp.zeros((b, kh, g, sq, dh), jnp.float32), vma)
    m = collectives.pvary(jnp.full((b, kh, g, sq, 1), NEG_INF, jnp.float32), vma)
    l = collectives.pvary(jnp.zeros((b, kh, g, sq, 1), jnp.float32), vma)

    def merge(carry, kc, vc, segc, kv_off):
        return _chunk_merge(carry, q, kc, vc, q_off, kv_off, scale,
                            segment_ids if has_seg else None,
                            segc if has_seg else None)

    def body(t, state):
        acc, m, l, kc, vc, segc = state
        src = (idx - t) % n  # who this kv chunk belongs to
        acc, m, l = merge((acc, m, l), kc, vc, segc, src * skv)
        kc, vc, segc = collectives.ring_exchange((kc, vc, segc), axis_name)
        return acc, m, l, kc, vc, segc

    # the rotating segment chunk; a dummy rides the ring when unpacked so
    # the loop structure is uniform
    seg0 = (segment_ids if has_seg
            else collectives.pvary(jnp.zeros((b, skv), jnp.int32), vma))
    # n-1 fold+rotate steps, then a final fold with no wasted rotation.
    acc, m, l, kc, vc, segc = lax.fori_loop(
        0, n - 1, body, (acc, m, l, k, v, seg0))
    acc, m, l = merge((acc, m, l), kc, vc, segc,
                      ((idx - (n - 1)) % n) * skv)
    out = acc / jnp.maximum(l, 1e-30)  # (B, KH, G, Sq, Dh)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, dh).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, segment_ids=None, scale=None,
                           batch_axes=("dp", "fsdp"), seq_axis="sp",
                           head_axis="tp"):
    """shard_map wrapper: full (B, S, H, Dh) arrays in, ring attention over
    the sp axis, full arrays out (still sharded by the same specs).
    segment_ids (B, S) shard over the sequence like the tokens."""
    qspec = P(batch_axes, seq_axis, head_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis, scale=scale)
    if segment_ids is None:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
            check_vma=True)(q, k, v)
    sspec = P(batch_axes, seq_axis)
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(qspec, qspec, qspec, sspec),
        out_specs=qspec, check_vma=True)(q, k, v, segment_ids)

"""Logical-axis sharding rules.

Every parameter and activation is annotated with *logical* axis names
("embed", "heads", "mlp", ...). A `ShardingRules` table maps logical names
to mesh axes (or None for replicated). This is the single place where the
parallelism layout of the whole framework is decided; models never mention
mesh axes directly.

The default table implements the standard megatron-style layout:
  * tensor parallelism (tp) shards heads / mlp / vocab,
  * fsdp shards the embed (weight-stationary) dimension of every matrix,
  * batch is data-parallel over (dp, fsdp), sequence over sp (ring attn),
  * experts over ep, pipeline stages over pp.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# logical axis name -> mesh axis (str), tuple of mesh axes, or None
ShardingRules = Mapping[str, Any]

DEFAULT_RULES: dict[str, Any] = {
    "batch": ("dp", "fsdp"),
    "sequence": "sp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "stages": "pp",
    "experts": "ep",
    "expert_mlp": "tp",
    "norm": None,
}


def spec_from_logical(logical_axes: Sequence[str | None],
                      rules: ShardingRules = DEFAULT_RULES) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    return P(*[rules[a] if a is not None else None for a in logical_axes])


def constrain(x, logical_axes: Sequence[str | None],
              rules: ShardingRules = DEFAULT_RULES):
    """Anchor an activation's sharding by logical axis names.

    `with_sharding_constraint` against the process-wide mesh — the way
    model code pins activation layouts (e.g. the sequence dim onto sp)
    without ever naming mesh axes. Degrades to a no-op when:
      * no mesh is registered (pure single-device library use),
      * called eagerly (unit tests poking at forwards outside jit),
      * every mesh axis the spec names has size 1 (nothing to anchor —
        also keeps a stale registered mesh from touching unrelated jits).
    """
    from cloud_server_tpu.parallel.mesh import maybe_current_mesh

    mesh = maybe_current_mesh()
    if mesh is None or not isinstance(x, jax.core.Tracer):
        return x
    spec = spec_from_logical(logical_axes, rules)
    named = [a for entry in spec if entry is not None
             for a in (entry if isinstance(entry, tuple) else (entry,))
             if a is not None]
    # A custom registered mesh may not carry the canonical axis names;
    # "degrades to a no-op" must hold there too.
    if any(a not in mesh.shape for a in named):
        return x
    if all(mesh.shape[a] == 1 for a in named):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def logical_to_sharding(logical_tree: Any, mesh: Mesh,
                        rules: ShardingRules = DEFAULT_RULES) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings.

    Leaves of `logical_tree` are tuples/lists of logical axis names (or None
    entries for replicated dims); structure must match the param pytree.
    """
    def leaf(axes):
        return NamedSharding(mesh, spec_from_logical(axes, rules))

    return jax.tree.map(
        leaf, logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list)) and
        all(isinstance(a, str) or a is None for a in x),
    )

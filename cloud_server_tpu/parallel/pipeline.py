"""Pipeline parallelism over the `pp` mesh axis.

SPMD GPipe: the layer stack is split into `pp` stages (the stacked layer
axis is sharded over the pp mesh axis, so each device holds L/pp layers).
Under `shard_map`, every device runs the same program: at step t it applies
its stage to the microbatch it holds, then `ppermute`s the activation to the
next stage. After M + pp - 1 steps all M microbatches have flowed through;
the last stage's collected outputs are broadcast with a masked psum.

This is differentiable end-to-end (ppermute has a transpose rule: the
reverse permutation), so the backward pass is the mirrored pipeline —
no hand-written schedule, XLA sees one fused program per device.

The bubble is the standard GPipe (pp - 1) / (M + pp - 1); raise
`num_microbatches` to amortise it.

Schedule design note: grad-of-SPMD-GPipe is deliberate on TPU. XLA derives
the backward pipeline (the transposed ring) from this one traced program,
so there is no hand-written 1F1B interleave — that would require manually
scheduling fwd/bwd microbatch ops against each other, which fights XLA's
whole-program compilation model. 1F1B's actual win, bounding live
activations to O(pp) instead of O(M) microbatches, is recovered
compositionally: wrap the pipelined loss in the train step's in-jit
gradient accumulation (`TrainConfig.microbatch_steps`) — each outer
accumulation step pipelines only M_inner microbatches, so peak liveness is
M_inner while the bubble amortises over M_inner * microbatch_steps
(tested in tests/test_pipeline.py::test_pipeline_composes_with_grad_accum).

Payloads are pytrees: the MoE stack pipelines with its router-stat
accumulators riding the ring next to the activations.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from cloud_server_tpu.models import transformer
from cloud_server_tpu.parallel import collectives


def pipeline_spmd(stage_params, microbatches, stage_fn: Callable,
                  *, axis_name: str = "pp"):
    """Run microbatches through the pipeline. Call under shard_map.

    Args:
      stage_params: this device's slice of the stacked layer params
        (leading layer axis length L/pp locally).
      microbatches: pytree of (M, mb, ...) replicated input microbatches —
        any pytree payload rides the ring (e.g. MoE activations plus their
        accumulated router-stat scalars).
      stage_fn: (stage_params, payload) -> payload applying this stage's
        layers; must preserve the payload's pytree structure/shapes.
      axis_name: the pipeline mesh axis.

    Returns:
      pytree of (M, mb, ...) outputs, replicated (valid on every device).
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = jax.tree.leaves(microbatches)[0].shape[0]
    t_total = m + pp - 1

    # Stage results vary over the pp axis (each stage computes different
    # values) and possibly over more axes than their inputs (e.g. MoE
    # router stats enter replicated but accumulate batch-sharded values).
    # Zero-init carries and injected microbatches must declare the stage
    # OUTPUT's varying-axes set up front or check_vma=True rejects the
    # cond/scan — so derive each payload leaf's target vma by abstract
    # evaluation of stage_fn.
    def promote(z, aval):
        missing = tuple(set(aval.vma) - set(jax.typeof(z).vma))
        return collectives.pvary(z, missing) if missing else z

    x_probe = jax.tree.map(
        lambda mb: collectives.pvary(mb[0], (axis_name,)), microbatches)
    y_avals = jax.eval_shape(
        lambda x: stage_fn(stage_params, x), x_probe)

    def body(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        x_in = jax.tree.map(
            lambda mb, r, av: jnp.where(
                stage == 0, promote(mb[mb_idx], av), r),
            microbatches, recv, y_avals)
        y = stage_fn(stage_params, x_in)
        out_idx = t - (pp - 1)
        is_valid_out = jnp.logical_and(stage == pp - 1, out_idx >= 0)
        outputs = lax.cond(
            is_valid_out,
            lambda o: jax.tree.map(
                lambda ol, yl: lax.dynamic_update_index_in_dim(
                    ol, yl, jnp.clip(out_idx, 0, m - 1), axis=0),
                o, y),
            lambda o: o,
            outputs)
        recv_next = collectives.ring_exchange(y, axis_name)
        return (recv_next, outputs), None

    recv0 = jax.tree.map(
        lambda mb, av: promote(jnp.zeros_like(mb[0]), av),
        microbatches, y_avals)
    outputs0 = jax.tree.map(
        lambda mb, av: promote(jnp.zeros_like(mb), av),
        microbatches, y_avals)
    (_, outputs), _ = lax.scan(body, (recv0, outputs0), jnp.arange(t_total))

    # Only the last stage holds real outputs; masked psum broadcasts them.
    return jax.tree.map(
        lambda o: collectives.psum(
            o * (stage == pp - 1).astype(o.dtype), axis_name),
        outputs)



def _is_moe_module(loss_fn_module) -> bool:
    """Capability check, not name sniffing: a module pipelines as MoE iff
    it exposes the (x, aux)-returning `_moe_block` stage primitive."""
    return hasattr(loss_fn_module, "_moe_block")


def _dense_stage_factory(model_cfg, cos, sin, attn_fn, packed=False):
    def stage_fn(stage_params, payload):
        if packed:
            # segment ids + per-document positions ride the ring with the
            # activations so every stage masks/ropes its microbatch right
            x, seg, pos = payload
            attn = transformer._packed_attention_fn(model_cfg, seg)
        else:
            x, seg, pos, attn = payload, None, None, attn_fn
        block = functools.partial(transformer._block, cfg=model_cfg,
                                  cos=cos, sin=sin, attn_fn=attn,
                                  positions=pos)
        block = transformer.apply_remat(block, model_cfg)

        def scan_body(h, lp):
            return block(h, lp), None

        out, _ = lax.scan(scan_body, x, stage_params)
        return (out, seg, pos) if packed else out
    return stage_fn


def _moe_stage_factory(model_cfg, cos, sin, attn_fn, packed=False):
    """MoE stage: payload is (x, aux3) — the three router stats
    (load_balance, router_z, dropped_frac) accumulate across layers and
    ride the ring with the activations."""
    from cloud_server_tpu.models import moe

    def stage_fn(stage_params, payload):
        if packed:
            x, aux3, seg, pos = payload
            attn = transformer._packed_attention_fn(model_cfg, seg)
        else:
            (x, aux3), seg, pos, attn = payload, None, None, attn_fn
        # aux3 enters replicated over the batch axes while x is sharded
        # over them; the scan carry must agree, so promote aux3 to x's vma.
        aux3 = collectives.pvary(aux3, tuple(
            set(jax.typeof(x).vma) - set(jax.typeof(aux3).vma)))
        block = functools.partial(moe._moe_block, cfg=model_cfg,
                                  cos=cos, sin=sin, attn_fn=attn,
                                  positions=pos)
        block = transformer.apply_remat(block, model_cfg)

        def scan_body(carry, lp):
            h, a = carry
            h, aux = block(h, lp)
            a = a + jnp.stack([aux["load_balance"], aux["router_z"],
                               aux["dropped_frac"]])
            return (h, a), None

        (x, aux3), _ = lax.scan(scan_body, (x, aux3), stage_params)
        return (x, aux3, seg, pos) if packed else (x, aux3)
    return stage_fn


def make_pipelined_hidden(model_cfg, mesh: Mesh, num_microbatches: int,
                          rules=None, loss_fn_module=transformer):
    """Return hidden(params, tokens) with the block stack run as a pipeline.

    Dense (`loss_fn_module=transformer`): hidden -> final-normed (B, S, D).
    MoE (`loss_fn_module=models.moe`): hidden -> (x, aux dict of averaged
    router stats), mirroring `moe.forward_hidden`.

    Embedding / final norm / head run replicated over pp (they are cheap
    relative to the stack); only the L-layer block scan is pipelined.
    """
    from cloud_server_tpu.ops import rms_norm, rope_table
    from cloud_server_tpu.parallel.sharding import DEFAULT_RULES

    rules = rules or DEFAULT_RULES
    pp = mesh.shape["pp"]
    if model_cfg.num_layers % pp:
        raise ValueError(f"num_layers={model_cfg.num_layers} not divisible "
                         f"by pp={pp}")
    is_moe = _is_moe_module(loss_fn_module)
    factory = _moe_stage_factory if is_moe else _dense_stage_factory

    layer_spec = P("pp")  # stacked layer axis sharded over pp
    batch_spec = P(rules["batch"])

    def hidden(params, tokens, segment_ids=None):
        cfg = model_cfg
        packed = segment_ids is not None
        if packed and cfg.attention_impl not in ("xla", "flash"):
            raise ValueError(
                "pipelined packed batches need attention_impl 'xla' or "
                "'flash' (ring/ulysses would nest shard_map inside the "
                f"pipeline shard_map); got {cfg.attention_impl!r}")
        cos, sin = rope_table(cfg, tokens.shape[1])
        x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]  # (B, S, D)
        b = x.shape[0]
        mb = b // num_microbatches
        micro_x = x.reshape((num_microbatches, mb) + x.shape[1:])
        seg_pos_specs = ()
        seg_pos = ()
        if packed:
            from cloud_server_tpu.ops.segments import positions_from_segments
            pos = positions_from_segments(segment_ids)
            mshape = (num_microbatches, mb, tokens.shape[1])
            seg_pos = (segment_ids.reshape(mshape), pos.reshape(mshape))
            seg_pos_specs = (P(None, *batch_spec[:1], None),
                             P(None, *batch_spec[:1], None))
        if is_moe:
            micro = (micro_x, jnp.zeros((num_microbatches, 3), jnp.float32),
                     *seg_pos)
            payload_spec = (P(None, *batch_spec), P(None, None),
                            *seg_pos_specs)
            if not packed:
                micro = micro[:2]
        else:
            micro = (micro_x, *seg_pos) if packed else micro_x
            payload_spec = ((P(None, *batch_spec), *seg_pos_specs)
                            if packed else P(None, *batch_spec))

        attn_fn = None if packed else transformer._get_attention_fn(cfg)
        stage_fn = factory(cfg, cos, sin, attn_fn, packed=packed)

        def pipe_fn(layers, micro_in):
            out = pipeline_spmd(layers, micro_in, stage_fn=stage_fn)
            if is_moe:
                # payload may carry (x, aux3[, seg, pos]); router stats
                # are per-batch-shard, averaged so the replicated
                # out_spec is truthful
                return (out[0], lax.pmean(out[1], rules["batch"]),
                        *out[2:])
            return out

        pipe = jax.shard_map(
            pipe_fn,
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: layer_spec, params["layers"]),
                      payload_spec),
            out_specs=payload_spec,
            check_vma=True,
        )
        micro_out = pipe(params["layers"], micro)
        if packed:
            micro_out = (micro_out[:2] if is_moe else micro_out[0])
        if is_moe:
            micro_x_out, aux_out = micro_out
            xo = rms_norm(micro_x_out.reshape(x.shape),
                          params["final_norm"]["scale"], cfg.norm_eps)
            # per-microbatch layer sums -> batch mean, per-layer mean
            avg = aux_out.mean(axis=0) / cfg.num_layers
            return xo, {"load_balance": avg[0], "router_z": avg[1],
                        "dropped_frac": avg[2]}
        xo = micro_out.reshape(x.shape)
        return rms_norm(xo, params["final_norm"]["scale"], cfg.norm_eps)

    return hidden


def make_pipelined_forward(model_cfg, mesh: Mesh, num_microbatches: int,
                           rules=None, loss_fn_module=transformer):
    """Return forward(params, tokens) with the block stack pipelined:
    dense -> (B, S, V) f32 logits; MoE -> (logits, aux dict), mirroring
    the unpipelined module forwards."""
    hidden = make_pipelined_hidden(model_cfg, mesh, num_microbatches, rules,
                                   loss_fn_module)
    is_moe = _is_moe_module(loss_fn_module)

    def forward(params, tokens, segment_ids=None):
        if is_moe:
            x, aux = hidden(params, tokens, segment_ids)
            return transformer.unembed(x, params, model_cfg), aux
        return transformer.unembed(hidden(params, tokens, segment_ids),
                                   params, model_cfg)

    return forward


def make_pipelined_loss(model_cfg, mesh: Mesh, num_microbatches: int,
                        z_loss_coef: float = 0.0, loss_fn_module=transformer,
                        aux_loss_coef: float = 0.01,
                        router_z_coef: float = 0.0):
    """Pipelined replacement for <module>.next_token_loss; same signature
    (params, batch, cfg) so it drops into make_train_step(loss_fn=...).

    Honors cfg.vocab_chunk and cfg.ce_impl (transformer.
    hidden_state_loss is the single dispatch point): chunked or fused
    CE instead of materialising (B, S, V) logits. With
    loss_fn_module=models.moe the MoE stack pipelines and the router
    aux losses match moe.next_token_loss.
    """
    hidden = make_pipelined_hidden(model_cfg, mesh, num_microbatches,
                                   loss_fn_module=loss_fn_module)
    is_moe = _is_moe_module(loss_fn_module)

    def loss_fn(params, batch, cfg):
        # The stack is built from the closed-over model_cfg; ignore the
        # runtime cfg so the head/softcap/chunking can't silently diverge
        # from the pipelined body.
        del cfg
        seg = batch.get("segment_ids")
        batch = transformer.apply_segment_loss_mask(batch)
        out = hidden(params, batch["tokens"], seg)
        x, aux = out if is_moe else (out, None)
        # single CE dispatch point: honors ce_impl AND vocab_chunk
        loss, metrics = transformer.hidden_state_loss(
            x, params, batch, model_cfg, z_loss_coef)
        if is_moe:
            metrics.update(load_balance=aux["load_balance"],
                           router_z=aux["router_z"],
                           dropped_frac=aux["dropped_frac"])
            loss = loss + aux_loss_coef * aux["load_balance"]
            if router_z_coef > 0.0:
                loss = loss + router_z_coef * aux["router_z"]
        return loss, metrics

    return loss_fn

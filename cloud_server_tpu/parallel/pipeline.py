"""Pipeline parallelism over the `pp` mesh axis.

SPMD GPipe: the layer stack is split into `pp` stages (the stacked layer
axis is sharded over the pp mesh axis, so each device holds L/pp layers).
Under `shard_map`, every device runs the same program: at step t it applies
its stage to the microbatch it holds, then `ppermute`s the activation to the
next stage. After M + pp - 1 steps all M microbatches have flowed through;
the last stage's collected outputs are broadcast with a masked psum.

This is differentiable end-to-end (ppermute has a transpose rule: the
reverse permutation), so the backward pass is the mirrored pipeline —
no hand-written schedule, XLA sees one fused program per device.

The bubble is the standard GPipe (pp - 1) / (M + pp - 1); raise
`num_microbatches` to amortise it.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from cloud_server_tpu.models import transformer
from cloud_server_tpu.parallel import collectives


def pipeline_spmd(stage_params, microbatches, stage_fn: Callable,
                  *, axis_name: str = "pp"):
    """Run microbatches through the pipeline. Call under shard_map.

    Args:
      stage_params: this device's slice of the stacked layer params
        (leading layer axis length L/pp locally).
      microbatches: (M, mb, ...) replicated input microbatches.
      stage_fn: (stage_params, x) -> y applying this stage's layers.
      axis_name: the pipeline mesh axis.

    Returns:
      (M, mb, ...) outputs, replicated (valid on every device).
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    t_total = m + pp - 1

    def body(carry, t):
        recv, outputs = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        x_in = jnp.where(stage == 0, microbatches[mb_idx], recv)
        y = stage_fn(stage_params, x_in)
        out_idx = t - (pp - 1)
        is_valid_out = jnp.logical_and(stage == pp - 1, out_idx >= 0)
        outputs = lax.cond(
            is_valid_out,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, m - 1), axis=0),
            lambda o: o,
            outputs)
        recv_next = collectives.ppermute_shift(y, axis_name, 1)
        return (recv_next, outputs), None

    recv0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(body, (recv0, outputs0), jnp.arange(t_total))

    # Only the last stage holds real outputs; masked psum broadcasts them.
    mask = (stage == pp - 1).astype(outputs.dtype)
    return collectives.psum(outputs * mask, axis_name)


def make_pipelined_hidden(model_cfg, mesh: Mesh, num_microbatches: int,
                          rules=None):
    """Return hidden(params, tokens) -> final-normed (B, S, D) with the
    block stack run as a pipeline.

    Embedding / final norm / head run replicated over pp (they are cheap
    relative to the stack); only the L-layer block scan is pipelined.
    """
    from cloud_server_tpu.ops import rms_norm, rope_frequencies
    from cloud_server_tpu.parallel.sharding import DEFAULT_RULES

    rules = rules or DEFAULT_RULES
    pp = mesh.shape["pp"]
    if model_cfg.num_layers % pp:
        raise ValueError(f"num_layers={model_cfg.num_layers} not divisible "
                         f"by pp={pp}")

    def stage_fn_factory(cos, sin, attn_fn):
        def stage_fn(stage_params, x):
            block = functools.partial(transformer._block, cfg=model_cfg,
                                      cos=cos, sin=sin, attn_fn=attn_fn)
            block = transformer.apply_remat(block, model_cfg)

            def scan_body(h, lp):
                return block(h, lp), None

            out, _ = lax.scan(scan_body, x, stage_params)
            return out
        return stage_fn

    layer_spec = P("pp")  # stacked layer axis sharded over pp
    batch_spec = P(rules["batch"])

    def hidden(params, tokens):
        cfg = model_cfg
        cos, sin = rope_frequencies(cfg.head_dim, tokens.shape[1],
                                    cfg.rope_theta)
        x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]  # (B, S, D)
        b = x.shape[0]
        mb = b // num_microbatches
        micro = x.reshape((num_microbatches, mb) + x.shape[1:])

        attn_fn = transformer._get_attention_fn(cfg)
        stage_fn = stage_fn_factory(cos, sin, attn_fn)

        pipe = jax.shard_map(
            functools.partial(pipeline_spmd, stage_fn=stage_fn),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: layer_spec, params["layers"]),
                      P(None, *batch_spec)),
            out_specs=P(None, *batch_spec),
            check_vma=False,
        )
        micro_out = pipe(params["layers"], micro)
        x = micro_out.reshape(x.shape)

        return rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)

    return hidden


def make_pipelined_forward(model_cfg, mesh: Mesh, num_microbatches: int,
                           rules=None):
    """Return forward(params, tokens) -> (B, S, V) f32 logits with the block
    stack pipelined (see make_pipelined_hidden)."""
    hidden = make_pipelined_hidden(model_cfg, mesh, num_microbatches, rules)

    def forward(params, tokens):
        return transformer.unembed(hidden(params, tokens), params, model_cfg)

    return forward


def make_pipelined_loss(model_cfg, mesh: Mesh, num_microbatches: int,
                        z_loss_coef: float = 0.0):
    """Pipelined replacement for transformer.next_token_loss; same signature
    (params, batch, cfg) so it drops into make_train_step(loss_fn=...).

    Honors cfg.vocab_chunk: with vocab_chunk > 0 the loss runs blockwise
    over the vocab (transformer.fused_cross_entropy) instead of
    materialising (B, S, V) logits."""
    hidden = make_pipelined_hidden(model_cfg, mesh, num_microbatches)

    def loss_fn(params, batch, cfg):
        # The stack is built from the closed-over model_cfg; ignore the
        # runtime cfg so the head/softcap/chunking can't silently diverge
        # from the pipelined body.
        del cfg
        x = hidden(params, batch["tokens"])
        if model_cfg.vocab_chunk > 0:
            return transformer.fused_cross_entropy(
                x, params, batch, model_cfg, z_loss_coef)
        logits = transformer.unembed(x, params, model_cfg)
        return transformer.masked_cross_entropy(logits, batch, z_loss_coef)

    return loss_fn

from cloud_server_tpu.models import transformer  # noqa: F401

"""Flagship dense decoder-only LM (LLaMA-style: RMSNorm, RoPE, GQA, SwiGLU).

Pure-functional: parameters are a plain dict pytree; `forward` is a pure
function. Layers are *stacked* (leading layer axis on every block parameter)
and executed with `lax.scan`, which keeps compile time O(1) in depth and
lets us apply one remat policy per layer. All heavy math is expressed as
einsums over bfloat16 activations so XLA tiles it onto the MXU.

Logical sharding axes are declared next to each parameter in
`param_logical_axes`; the actual mesh layout comes from
`parallel.sharding.DEFAULT_RULES`.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.ops import (apply_rope, causal_attention, rms_norm,
                                  rope_table, swiglu)
from cloud_server_tpu.parallel.sharding import constrain

Params = dict

NEG_INF = -1e30  # finite stand-in for -inf (keeps exp/where NaN-free)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def param_shapes(cfg: ModelConfig) -> dict[str, Any]:
    L, D, H, KH, Dh, F, V = (cfg.num_layers, cfg.embed_dim, cfg.num_heads,
                             cfg.num_kv_heads, cfg.head_dim, cfg.mlp_dim,
                             cfg.vocab_size)
    shapes = {
        "embed": {"tokens": (V, D)},
        "layers": {
            "attn_norm": (L, D),
            "mlp_norm": (L, D),
            "wq": (L, D, H, Dh),
            "wk": (L, D, KH, Dh),
            "wv": (L, D, KH, Dh),
            "wo": (L, H, Dh, D),
            "w_gate": (L, D, F),
            "w_up": (L, D, F),
            "w_down": (L, F, D),
        },
        "final_norm": {"scale": (D,)},
    }
    if not cfg.tie_embeddings:
        shapes["lm_head"] = {"kernel": (D, V)}
    return shapes


def param_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    """Same structure as params; leaves are tuples of logical axis names."""
    axes = {
        "embed": {"tokens": ("vocab", "embed")},
        "layers": {
            "attn_norm": ("layers", "norm"),
            "mlp_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads", "head_dim"),
            "wk": ("layers", "embed", "kv_heads", "head_dim"),
            "wv": ("layers", "embed", "kv_heads", "head_dim"),
            "wo": ("layers", "heads", "head_dim", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": {"scale": ("norm",)},
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = {"kernel": ("embed", "vocab")}
    return axes


def _fan_in(name: str, cfg: ModelConfig) -> int:
    D, H, KH, Dh, F = (cfg.embed_dim, cfg.num_heads, cfg.num_kv_heads,
                       cfg.head_dim, cfg.mlp_dim)
    table = {"tokens": D, "kernel": D, "wq": D, "wk": D, "wv": D,
             "wo": H * Dh, "w_gate": D, "w_up": D, "w_down": F}
    return table[name]


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    """Truncated-normal init, std 1/sqrt(fan_in); norm scales init to 1."""
    dtype = jnp.dtype(cfg.param_dtype)
    shapes = param_shapes(cfg)
    paths, treedef = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(paths))

    out = []
    for (path, shape), key in zip(paths, keys):
        name = path[-1].key
        path_str = "/".join(p.key for p in path)
        if "norm" in path_str:
            out.append(jnp.ones(shape, dtype))
        else:
            std = 1.0 / math.sqrt(_fan_in(name, cfg))
            out.append(
                (jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)
                 * std).astype(dtype))
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def lora_row_delta(h, ab) -> jnp.ndarray:
    """Per-ROW low-rank delta for multi-adapter serving: each batch row
    carries its own (A, B) pair (gathered from a stacked adapter set by
    the row's adapter id). h: (B, S, Din); ab = (a (B, Din, r),
    b (B, r, Dout), scale (B,)) -> (B, S, Dout)."""
    a, b, scale = ab
    z = jnp.einsum("bsd,bdr->bsr", h, a.astype(h.dtype))
    d = jnp.einsum("bsr,bro->bso", z, b.astype(h.dtype))
    return d * scale[:, None, None].astype(h.dtype)


def attention_qkv(x, lp, cfg: ModelConfig, cos, sin, positions=None,
                  lora=None):
    """Pre-norm + q/k/v projection + rope. Single source of truth for the
    attention input path — the inference engine's prefill/decode reuse this
    so cached inference can never drift numerically from training.

    `lora` (serving only): {target: (a, b, scale)} per-row adapters —
    deltas land BEFORE rope, exactly where a merged weight would."""
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
    k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
    v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
    if lora:
        if "wq" in lora:
            q = q + lora_row_delta(h, lora["wq"]).reshape(q.shape)
        if "wk" in lora:
            k = k + lora_row_delta(h, lora["wk"]).reshape(k.shape)
        if "wv" in lora:
            v = v + lora_row_delta(h, lora["wv"]).reshape(v.shape)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    return q, k, v


def attention_out(x, o, lp, cfg: ModelConfig, lora=None):
    """Output projection + residual add (the attention block's second half)."""
    y = jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
    if lora and "wo" in lora:
        b_, s_ = o.shape[:2]
        y = y + lora_row_delta(o.reshape(b_, s_, -1), lora["wo"])
    return x + y


def _attention_block(x, lp, cfg: ModelConfig, cos, sin, attn_fn,
                     positions=None):
    q, k, v = attention_qkv(x, lp, cfg, cos, sin, positions)
    o = attn_fn(q, k, v)
    return attention_out(x, o, lp, cfg)


def mlp_block(x, lp, cfg: ModelConfig, lora=None):
    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jnp.einsum("bsd,df->bsf", h, lp["w_gate"].astype(cfg.dtype))
    up = jnp.einsum("bsd,df->bsf", h, lp["w_up"].astype(cfg.dtype))
    if lora:
        if "w_gate" in lora:
            gate = gate + lora_row_delta(h, lora["w_gate"])
        if "w_up" in lora:
            up = up + lora_row_delta(h, lora["w_up"])
    act = swiglu(gate, up)
    down = jnp.einsum("bsf,fd->bsd", act, lp["w_down"].astype(cfg.dtype))
    if lora and "w_down" in lora:
        down = down + lora_row_delta(act, lora["w_down"])
    return x + down


def _unembed_head(params: Params, cfg: ModelConfig) -> jnp.ndarray:
    return (params["embed"]["tokens"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])


def unembed(x, params: Params, cfg: ModelConfig) -> jnp.ndarray:
    """Final-norm'd hidden states (..., D) -> softcapped f32 logits (..., V)."""
    head = _unembed_head(params, cfg)
    logits = jnp.einsum("...d,dv->...v", x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return apply_logits_softcap(logits, cfg)


def _block(x, layer_params, cfg: ModelConfig, cos, sin, attn_fn,
           positions=None):
    x = _attention_block(x, layer_params, cfg, cos, sin, attn_fn, positions)
    x = mlp_block(x, layer_params, cfg)
    return x


def apply_remat(block, cfg: ModelConfig):
    """Wrap a layer-block fn with the configured remat policy.

    The single policy-selection point for the dense stack, the MoE stack,
    and the pipelined stack — keep them identical. Policies:
      * "none": save everything (no checkpoint).
      * "full": recompute everything.
      * "dots": save matmul outputs AND the flash kernel's (out, lse)
        residuals — pallas calls aren't dots, so without the name policy
        the backward re-runs the whole flash forward just to rebuild them.
      * "attn": save ONLY the flash residuals; recompute everything else
        (incl. the big (B, S, mlp_dim) gate/up tensors, whose dots-policy
        saves can cost more HBM traffic than their recompute FLOPs). Only
        meaningful with attention_impl="flash" — other impls emit no named
        residuals, making this equivalent to "full".
    """
    if cfg.remat == "none":
        return block
    if cfg.remat == "full":
        return jax.checkpoint(block)
    if cfg.remat == "dots":
        return jax.checkpoint(
            block, policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse")))
    if cfg.remat == "attn":
        return jax.checkpoint(
            block, policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
    raise ValueError(f"unknown remat policy: {cfg.remat!r}")


def _get_attention_fn(cfg: ModelConfig, segment_ids=None):
    """The one attention-impl dispatch table, with or without a packed
    segment mask (both callers — plain and packed forward — use this, so
    segment support for a new impl lands everywhere at once)."""
    if cfg.attention_impl == "xla":
        if segment_ids is None:
            return causal_attention
        return partial(causal_attention, segment_ids=segment_ids)
    if cfg.attention_impl == "flash":
        from cloud_server_tpu.ops.flash_attention import flash_attention
        return partial(flash_attention, segment_ids=segment_ids,
                       block_q=cfg.flash_block_q,
                       block_kv=cfg.flash_block_kv)
    if cfg.attention_impl == "ring":
        from cloud_server_tpu.parallel.mesh import current_mesh
        from cloud_server_tpu.parallel.ring_attention import (
            ring_attention_sharded)

        mesh = current_mesh()

        def ring_fn(q, k, v):
            return ring_attention_sharded(q, k, v, mesh,
                                          segment_ids=segment_ids)

        return ring_fn
    if cfg.attention_impl == "ulysses":
        from cloud_server_tpu.parallel.mesh import current_mesh
        from cloud_server_tpu.parallel.ulysses import (
            ulysses_attention_sharded)

        mesh = current_mesh()

        def ulysses_fn(q, k, v):
            return ulysses_attention_sharded(q, k, v, mesh,
                                             segment_ids=segment_ids)

        return ulysses_fn
    raise ValueError(f"unknown attention_impl: {cfg.attention_impl!r}")


def _packed_attention_fn(cfg: ModelConfig, segment_ids):
    """Back-compat alias: the packed variant of the dispatch table."""
    return _get_attention_fn(cfg, segment_ids)


def apply_segment_loss_mask(batch: dict) -> dict:
    """If the batch is packed, fold the segment boundary/padding mask into
    batch['mask'] (shared by the dense and MoE losses). No-op otherwise."""
    seg = batch.get("segment_ids")
    if seg is None:
        return batch
    from cloud_server_tpu.ops.segments import segment_target_mask
    tmask = segment_target_mask(seg)
    if batch.get("mask") is not None:
        tmask = tmask * batch["mask"].astype(tmask.dtype)
    return {**batch, "mask": tmask}


def forward_hidden(params: Params, tokens: jnp.ndarray,
                   cfg: ModelConfig,
                   segment_ids: jnp.ndarray | None = None) -> jnp.ndarray:
    """(B, S) int32 -> final-normed hidden states (B, S, D) in cfg.dtype.

    segment_ids: optional (B, S) packed-sequence ids (data/packing.py) —
    attention becomes block-diagonal causal and RoPE positions restart per
    document, so each packed document sees exactly the math it would see
    alone.
    """
    cos, sin = rope_table(cfg, tokens.shape[1])
    # Unshard the table's embed dim BEFORE the lookup: a tp-sharded D at
    # the gather makes XLA produce a D-sharded (B, S, D) it must then
    # replicate-and-repartition to the batch/sequence layout ("Involuntary
    # full rematerialization" in the SPMD partitioner). One table
    # all-gather per forward is strictly cheaper.
    table = constrain(params["embed"]["tokens"].astype(cfg.dtype),
                      ("vocab", None))
    x = table[tokens]
    # Anchor the residual stream to (batch, sequence, -) so that with
    # sp > 1 every per-position op (norms, MLP, fused CE) computes S/sp per
    # device; only ring attention's shard_map sees the full sequence.
    x = constrain(x, ("batch", "sequence", None))
    positions = None
    if segment_ids is not None:
        from cloud_server_tpu.ops.segments import positions_from_segments
        positions = positions_from_segments(segment_ids)
        attn_fn = _packed_attention_fn(cfg, segment_ids)
    else:
        attn_fn = _get_attention_fn(cfg)

    block = partial(_block, cfg=cfg, cos=cos, sin=sin, attn_fn=attn_fn,
                    positions=positions)
    block = apply_remat(block, cfg)

    def scan_body(carry, layer_params):
        return block(carry, layer_params), None

    x, _ = lax.scan(scan_body, x, params["layers"],
                    unroll=cfg.scan_layers_unroll)
    x = constrain(x, ("batch", "sequence", None))
    return rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)


def forward(params: Params, tokens: jnp.ndarray, cfg: ModelConfig,
            segment_ids: jnp.ndarray | None = None) -> jnp.ndarray:
    """Full-sequence forward pass: (B, S) int32 -> (B, S, V) float32 logits."""
    return unembed(forward_hidden(params, tokens, cfg, segment_ids),
                   params, cfg)


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------

def apply_logits_softcap(logits: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    if cfg.logits_softcap > 0:
        return cfg.logits_softcap * jnp.tanh(logits / cfg.logits_softcap)
    return logits


def masked_cross_entropy(logits: jnp.ndarray, batch: dict,
                         z_loss_coef: float = 0.0):
    """Shared next-token CE over full-S logits.

    logits: (B, S, V) f32 for the full sequence (the last position is
    dropped here); batch: {"tokens": (B, S), optional "mask": (B, S)}.
    Returns (loss, metrics).
    """
    tokens = batch["tokens"]
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    mask = batch.get("mask")
    mask = jnp.ones(targets.shape, jnp.float32) if mask is None else (
        mask[:, 1:].astype(jnp.float32))

    logz = jax.nn.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - target_logit
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    metrics = {"loss": loss, "ppl_log": loss,
               "accuracy": ((logits.argmax(-1) == targets) * mask).sum() / denom}
    if z_loss_coef > 0.0:
        z = (jnp.square(logz) * mask).sum() / denom
        loss = loss + z_loss_coef * z
        metrics["z_loss"] = z
    return loss, metrics


def _chunked_logz_target_argmax(x, head, targets, cfg: ModelConfig):
    """Blockwise-vocab logsumexp + target-logit gather + running argmax.

    x: (B, S, D) activations; head: (D, V); targets: (B, S) int32.
    Returns (logz, target_logit, argmax_idx), each (B, S) f32/f32/int32,
    numerically identical (up to accumulation order) to the dense path —
    without ever materialising (B, S, V) logits. The scan body is
    `jax.checkpoint`ed, so the backward pass also recomputes logits one
    chunk at a time instead of saving them.
    """
    D, V = head.shape
    C = cfg.vocab_chunk
    nc = -(-V // C)
    if nc * C != V:
        head = jnp.pad(head, ((0, 0), (0, nc * C - V)))
    head_c = jnp.moveaxis(head.reshape(D, nc, C), 1, 0)  # (nc, D, C)
    B, S, _ = x.shape

    def body(carry, inp):
        m, l, tgt, bidx = carry
        base, hc = inp
        logits = jnp.einsum("bsd,dc->bsc", x, hc.astype(cfg.dtype),
                            preferred_element_type=jnp.float32)
        logits = apply_logits_softcap(logits, cfg)
        col = base + jax.lax.broadcasted_iota(jnp.int32, (1, 1, C), 2)
        logits = jnp.where(col < V, logits, NEG_INF)  # padded tail
        mc = logits.max(-1)
        # m doubles as the running best-logit, so the argmax update must
        # compare against the pre-update m.
        am = base + jnp.argmax(logits, axis=-1).astype(jnp.int32)
        bidx = jnp.where(mc > m, am, bidx)
        m_new = jnp.maximum(m, mc)
        l = l * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[..., None]).sum(-1)
        in_chunk = (targets >= base) & (targets < base + C)
        off = jnp.clip(targets - base, 0, C - 1)
        tl = jnp.take_along_axis(logits, off[..., None], axis=-1)[..., 0]
        tgt = jnp.where(in_chunk, tl, tgt)
        return (m_new, l, tgt, bidx), None

    neg = jnp.full((B, S), NEG_INF, jnp.float32)
    init = (neg, jnp.zeros((B, S), jnp.float32), neg,
            jnp.zeros((B, S), jnp.int32))
    bases = jnp.arange(nc, dtype=jnp.int32) * C
    (m, l, tgt, bidx), _ = lax.scan(
        jax.checkpoint(body), init, (bases, head_c))
    return m + jnp.log(l), tgt, bidx


def _shifted_targets_mask(batch: dict):
    """The full-length next-token pairing both hidden-state CE impls
    share: position i predicts token i+1; the last position is masked
    out, so the sequence dim keeps its full (sp-divisible) length."""
    tokens = batch["tokens"]
    targets = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
    mask = batch.get("mask")
    mask = jnp.ones(tokens.shape, jnp.float32) if mask is None else (
        mask.astype(jnp.float32))
    mask = jnp.concatenate(
        [mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
    return targets, mask


def _stats_loss(logz, target_logit, argmax_idx, targets, mask,
                z_loss_coef: float):
    """(loss, metrics) from per-position CE statistics — the single
    epilogue for every stats-producing CE implementation."""
    nll = logz - target_logit
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    metrics = {"loss": loss, "ppl_log": loss,
               "accuracy": ((argmax_idx == targets) * mask).sum() / denom}
    if z_loss_coef > 0.0:
        z = (jnp.square(logz) * mask).sum() / denom
        loss = loss + z_loss_coef * z
        metrics["z_loss"] = z
    return loss, metrics


def fused_cross_entropy(x, params: Params, batch: dict, cfg: ModelConfig,
                        z_loss_coef: float = 0.0):
    """Next-token CE over final hidden states, chunked over the vocab
    (lax.scan; see `_chunked_logz_target_argmax`). Same contract and
    metrics as `masked_cross_entropy`."""
    targets, mask = _shifted_targets_mask(batch)
    head = _unembed_head(params, cfg)
    logz, target_logit, argmax_idx = _chunked_logz_target_argmax(
        x, head, targets, cfg)
    return _stats_loss(logz, target_logit, argmax_idx, targets, mask,
                       z_loss_coef)


def pallas_cross_entropy(x, params: Params, batch: dict,
                         cfg: ModelConfig, z_loss_coef: float = 0.0):
    """Next-token CE via the fused pallas kernels (ops/fused_ce.py):
    same contract and metrics as `fused_cross_entropy`, but the
    per-row (logz, target_logit, argmax) statistics come out of an
    online-logsumexp kernel — no f32 logits in HBM, and the backward's
    matmuls run in the model dtype (its one (B*S, V) buffer is the
    model-dtype d_logits; see ops/fused_ce.py)."""
    from cloud_server_tpu.ops.fused_ce import fused_ce_stats

    b, s = batch["tokens"].shape
    targets, mask = _shifted_targets_mask(batch)
    head = _unembed_head(params, cfg).astype(cfg.dtype)
    logz, target_logit, argmax_idx = fused_ce_stats(
        x.reshape(b * s, -1), head, targets.reshape(-1))
    return _stats_loss(logz.reshape(b, s), target_logit.reshape(b, s),
                       argmax_idx.reshape(b, s), targets, mask,
                       z_loss_coef)


def hidden_state_loss(x, params: Params, batch: dict, cfg: ModelConfig,
                      z_loss_coef: float = 0.0):
    """Next-token CE from final hidden states — THE dispatch point for
    every hidden-state loss path (dense stack, MoE, pipelined), so a
    ce_impl/vocab_chunk setting can never be silently ignored by one
    of them: ce_impl='pallas' -> fused kernels; vocab_chunk > 0 ->
    scan-chunked; else dense unembed + masked CE."""
    if cfg.ce_impl == "pallas":
        return pallas_cross_entropy(x, params, batch, cfg, z_loss_coef)
    if cfg.vocab_chunk > 0:
        return fused_cross_entropy(x, params, batch, cfg, z_loss_coef)
    logits = unembed(x, params, cfg)
    return masked_cross_entropy(logits, batch, z_loss_coef)


def next_token_loss(params: Params, batch: dict, cfg: ModelConfig,
                    z_loss_coef: float = 0.0):
    """Causal LM loss. batch: {"tokens": (B, S) int32, optional
    "mask": (B, S), optional "segment_ids": (B, S) for packed rows}.

    Predicts tokens[:, 1:] from tokens[:, :-1]. Forward runs on the full S
    (not S-1) so the sequence stays divisible for sp-sharded attention; the
    last position is dropped inside the loss. With cfg.vocab_chunk > 0 the
    logits never materialise (see `fused_cross_entropy`); with
    cfg.ce_impl == "pallas" they never do either, via the fused kernels
    (see `pallas_cross_entropy`). With segment_ids,
    attention/positions follow the packing (see `forward_hidden`) and
    targets crossing a document boundary (or in padding) are masked out
    of the loss.
    """
    seg = batch.get("segment_ids")
    batch = apply_segment_loss_mask(batch)
    if cfg.ce_impl == "pallas" or cfg.vocab_chunk > 0:
        x = forward_hidden(params, batch["tokens"], cfg, segment_ids=seg)
        return hidden_state_loss(x, params, batch, cfg, z_loss_coef)
    logits = forward(params, batch["tokens"], cfg, segment_ids=seg)
    return masked_cross_entropy(logits, batch, z_loss_coef)

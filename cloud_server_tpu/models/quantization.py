"""Weight-only int8 quantization for inference.

TPU-first rationale: decode is HBM-bandwidth-bound — every step streams all
weights once per token. Storing weights as int8 with per-output-channel
float32 scales halves (vs bf16) the bytes streamed, and XLA fuses the
dequantize (`convert` + `multiply`) into the consuming matmul, so the MXU
still sees bf16 operands and there is no extra HBM round-trip.

Mechanism: every weight in this codebase is consumed via
`w.astype(cfg.dtype)` immediately before its einsum
(models/transformer.py:119-151, models/moe.py:106-118). `QTensor` is a
registered pytree node whose `.astype()` performs the dequantize — so
quantized parameter trees flow through the *unmodified* model, engine, and
`lax.scan` layer-stacking machinery (scan slices the leading layer axis of
both the int8 payload and its scales in lockstep).

Scales are symmetric per-output-channel, constant along every contracted
axis of the consuming einsum (`_REDUCE_AXES` below), which is what makes
scaling-after-matmul exact. Router weights, norm scales, and embeddings are
left in full precision: routers are numerically sensitive, norms are tiny,
and the embedding is consumed by gather (not a contraction) — its lm_head
use when `tie_embeddings=True` would need a transpose-aware scale.

Inference-only: `QTensor` defines no VJP — training stays in bf16/f32.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# (leaf name, ndim) -> axes of the *stacked* weight that are contracted by
# its consuming einsum. Scales reduce over exactly these axes, so they stay
# per-output-channel (and per-layer, per-expert) everywhere else.
_REDUCE_AXES: dict[tuple[str, int], tuple[int, ...]] = {
    # dense attention (L, D, H|KH, Dh): contract D
    ("wq", 4): (1,), ("wk", 4): (1,), ("wv", 4): (1,),
    # attention out (L, H, Dh, D): contract H, Dh
    ("wo", 4): (1, 2),
    # dense MLP (L, D, F) / (L, F, D): contract axis 1
    ("w_gate", 3): (1,), ("w_up", 3): (1,), ("w_down", 3): (1,),
    # MoE experts (L, E, D, F) / (L, E, F, D): contract axis 2
    ("w_gate", 4): (2,), ("w_up", 4): (2,), ("w_down", 4): (2,),
    # untied lm_head (D, V): contract D
    ("kernel", 2): (0,),
}


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 payload + broadcastable f32 scales; dequantizes on `.astype`."""

    def __init__(self, q: jnp.ndarray, scale: jnp.ndarray):
        self.q = q
        self.scale = scale

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    # -- array-like surface used by the models ------------------------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    def astype(self, dtype) -> jnp.ndarray:
        """Dequantize. f32 multiply keeps full scale precision; the final
        cast (and the multiply itself) fuse into the consuming matmul."""
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def dequantize(self) -> jnp.ndarray:
        return self.q.astype(jnp.float32) * self.scale

    def __repr__(self):
        return f"QTensor(q={self.q.shape}, scale={self.scale.shape})"


def quantize(w: jnp.ndarray, reduce_axes: tuple[int, ...]) -> QTensor:
    """Symmetric int8 quantization with scales reduced over `reduce_axes`."""
    w = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QTensor(q, scale)


def quantize_params(params: Any) -> Any:
    """Quantize every weight with a `_REDUCE_AXES` entry; pass the rest
    through untouched. Works for dense, MoE, and LoRA-merged trees."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = path[-1].key if hasattr(path[-1], "key") else None
        # ("kernel", 2) is keyed on the generic name "kernel"; its axis-0
        # scales are only correct for the lm_head (D, V) matrix, so gate on
        # the parent key rather than quantizing any stray 2-D "kernel".
        if name == "kernel" and not (
                len(path) >= 2 and getattr(path[-2], "key", None) == "lm_head"):
            out.append(leaf)
            continue
        axes = _REDUCE_AXES.get((name, getattr(leaf, "ndim", -1)))
        out.append(quantize(leaf, axes) if axes is not None else leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize_params(params: Any) -> Any:
    """Inverse of `quantize_params` (lossy): QTensor leaves -> f32 arrays."""
    return jax.tree.map(
        lambda x: x.dequantize() if isinstance(x, QTensor) else x,
        params, is_leaf=lambda x: isinstance(x, QTensor))


def quantized_bytes(params: Any) -> tuple[int, int]:
    """(bytes as stored, bytes if everything were bf16) — for reporting."""
    stored = 0
    bf16 = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            stored += leaf.q.size + 4 * leaf.scale.size
            bf16 += 2 * leaf.q.size
        else:
            stored += leaf.dtype.itemsize * leaf.size
            bf16 += 2 * leaf.size
    return stored, bf16


def quantized_shardings(qparams: Any, logical_tree: Any, mesh,
                        rules=None) -> Any:
    """Sharding tree for a quantized param tree, for `jax.device_put`.

    `logical_tree` is the model's `param_logical_axes(cfg)` (unquantized
    structure: one axis tuple per weight). For each QTensor the int8
    payload takes the weight's own spec; its scales take the same spec with
    the *contracted* axes replaced by None — those dims are size 1 and
    cannot be sharded, and replicating scales along the contraction is what
    keeps the post-matmul rescale local to each shard.
    """
    from jax.sharding import NamedSharding

    from cloud_server_tpu.parallel.sharding import (
        DEFAULT_RULES, spec_from_logical)

    rules = rules or DEFAULT_RULES
    is_q = lambda x: isinstance(x, QTensor)

    def leaf(path, qleaf, axes):
        spec = spec_from_logical(axes, rules)
        if not is_q(qleaf):
            return NamedSharding(mesh, spec)
        name = path[-1].key if hasattr(path[-1], "key") else None
        reduce_axes = _REDUCE_AXES[(name, qleaf.ndim)]
        scale_axes = tuple(None if i in reduce_axes else a
                           for i, a in enumerate(axes))
        return QTensor(NamedSharding(mesh, spec),
                       NamedSharding(mesh, spec_from_logical(scale_axes,
                                                             rules)))

    flat, treedef = jax.tree_util.tree_flatten_with_path(
        qparams, is_leaf=is_q)
    axes_flat = jax.tree.leaves(
        logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list)) and
        all(isinstance(a, str) or a is None for a in x))
    assert len(flat) == len(axes_flat), "param/axes tree mismatch"
    out = [leaf(path, q, axes) for (path, q), axes in zip(flat, axes_flat)]
    return jax.tree_util.tree_unflatten(treedef, out)

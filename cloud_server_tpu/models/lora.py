"""LoRA fine-tuning: low-rank adapters over the dense or MoE LM.

Design (functional, jit-first):
  * Adapter params live BESIDE the frozen base in one pytree
    {"base": ..., "lora": {"layers": {target: {"a", "b"}}}} — one TrainState,
    one checkpoint, one sharded restore path; nothing else in the framework
    needs to know about LoRA.
  * The forward path *merges* W' = W + (alpha/r)·A@B per target and calls
    the base module unchanged (`merge_lora`), so every attention impl
    (xla/flash/ring), remat policy, and the inference engine work with
    adapters for free. The merge is a rank-r matmul per target — negligible
    next to the forward itself for r ≪ min(fan_in, fan_out).
  * The base is frozen two ways: `stop_gradient` in the loss (XLA dead-code
    eliminates the whole base backward pass) and an optimizer label mask
    (`param_labels`) that gives base params `optax.set_to_zero()` — so no
    Adam moments are allocated for them (the TrainState stays adapter-sized
    in optimizer memory, the point of LoRA at scale).
  * `export_merged` folds trained adapters back into plain base params for
    serving (the inference engine and server take them as-is).

A/B are stored flat — A: (L, fan_in, r), B: (L, r, fan_out) — replicated
across the mesh except the layer axis (they are tiny; sharding them would
only add collectives).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.models import transformer

# target name -> (stack axis names between the layer axis and fan-in,
# number of trailing output dims). Everything between the stack axes and
# the output dims is fan-in; adapters get one (A, B) pair per stack entry
# — for MoE expert weights (L, E, D, F) that means PER-EXPERT adapters
# A (L, E, D, r), B (L, E, r, F).
_DENSE_TARGETS: dict[str, tuple[tuple[str, ...], int]] = {
    "wq": ((), 2), "wk": ((), 2), "wv": ((), 2),  # (L, D, H, Dh)
    "wo": ((), 1),                                 # (L, H, Dh, D)
    "w_gate": ((), 1), "w_up": ((), 1),            # (L, D, F)
    "w_down": ((), 1),                             # (L, F, D)
}
_MOE_TARGETS: dict[str, tuple[tuple[str, ...], int]] = {
    "wq": ((), 2), "wk": ((), 2), "wv": ((), 2),
    "wo": ((), 1),
    "router": ((), 1),                             # (L, D, E)
    "w_gate": (("experts",), 1),                   # (L, E, D, F)
    "w_up": (("experts",), 1),
    "w_down": (("experts",), 1),                   # (L, E, F, D)
}
_TARGETS = {**_DENSE_TARGETS, **_MOE_TARGETS}  # union, for validation

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


def _target_table(base_module) -> dict[str, tuple[tuple[str, ...], int]]:
    if base_module is transformer:
        return _DENSE_TARGETS
    from cloud_server_tpu.models import moe
    if base_module is moe:
        return _MOE_TARGETS
    raise NotImplementedError(
        f"LoRA target table not defined for module {base_module!r}")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple[str, ...] = DEFAULT_TARGETS

    def __post_init__(self):
        unknown = set(self.targets) - set(_TARGETS)
        if unknown:
            raise ValueError(f"unknown LoRA targets {sorted(unknown)}; "
                             f"valid: {sorted(_TARGETS)}")
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


_SIDECAR = "lora_config.json"


def save_lora_config(checkpoint_dir: str | os.PathLike,
                     cfg: LoRAConfig) -> None:
    """Persist the adapter hyperparameters next to the checkpoint. alpha
    only enters the math at merge time, so an unrecorded training alpha
    would silently rescale the served model."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    with open(os.path.join(os.fspath(checkpoint_dir), _SIDECAR), "w") as f:
        json.dump(dataclasses.asdict(cfg), f)


def load_lora_config(checkpoint_dir: str | os.PathLike) -> LoRAConfig | None:
    path = os.path.join(os.fspath(checkpoint_dir), _SIDECAR)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        data = json.load(f)
    data["targets"] = tuple(data["targets"])
    return LoRAConfig(**data)


def add_lora_args(parser) -> None:
    """The one definition of the --lora-* CLI surface (train + generate)."""
    parser.add_argument("--lora-rank", type=int, default=0, metavar="R",
                        help="rank-R LoRA adapters (0 = no LoRA)")
    parser.add_argument("--lora-alpha", type=float, default=16.0)
    parser.add_argument("--lora-targets", default=",".join(DEFAULT_TARGETS),
                        help="comma-separated projection names to adapt")


def lora_config_from_args(args) -> LoRAConfig | None:
    if args.lora_rank <= 0:
        return None
    return LoRAConfig(rank=args.lora_rank, alpha=args.lora_alpha,
                      targets=tuple(args.lora_targets.split(",")))


def _split_dims(name: str, shape: tuple[int, ...], table=None
                ) -> tuple[tuple[int, ...], int, int]:
    """(stack dims, fan_in, fan_out) of a stacked (L, *stack, ...) base
    weight, fan-in/out flattened."""
    stack_axes, n_out = (table or _DENSE_TARGETS)[name]
    n_stack = len(stack_axes)
    stack = shape[1:1 + n_stack]
    fan_in = math.prod(shape[1 + n_stack:-n_out])
    fan_out = math.prod(shape[-n_out:])
    return stack, fan_in, fan_out


def init_lora_params(model_cfg: ModelConfig, lora_cfg: LoRAConfig,
                     rng: jax.Array, base_module=transformer) -> dict:
    """A ~ N(0, 1/fan_in), B = 0 — the adapted delta starts at exactly 0.
    Stacked targets (MoE expert weights) get one adapter pair per stack
    entry: A (L, E, fan_in, r), B (L, E, r, fan_out)."""
    table = _target_table(base_module)
    bad = set(lora_cfg.targets) - set(table)
    if bad:
        raise ValueError(
            f"LoRA targets {sorted(bad)} do not exist for this model "
            f"family (valid here: {sorted(table)})")
    shapes = base_module.param_shapes(model_cfg)["layers"]
    keys = jax.random.split(rng, len(lora_cfg.targets))
    out: dict[str, Any] = {"layers": {}}
    for key, name in zip(keys, sorted(lora_cfg.targets)):
        L = shapes[name][0]
        stack, fan_in, fan_out = _split_dims(name, shapes[name], table)
        a = (jax.random.truncated_normal(
            key, -2.0, 2.0, (L, *stack, fan_in, lora_cfg.rank),
            jnp.float32)
            / math.sqrt(fan_in)).astype(jnp.dtype(model_cfg.param_dtype))
        b = jnp.zeros((L, *stack, lora_cfg.rank, fan_out),
                      jnp.dtype(model_cfg.param_dtype))
        out["layers"][name] = {"a": a, "b": b}
    return out


def lora_logical_axes(model_cfg: ModelConfig, lora_cfg: LoRAConfig,
                      base_module=transformer) -> dict:
    table = _target_table(base_module)
    out = {}
    for name in sorted(lora_cfg.targets):
        stack_axes = table[name][0]
        out[name] = {"a": ("layers", *stack_axes, None, None),
                     "b": ("layers", *stack_axes, None, None)}
    return {"layers": out}


def merge_lora(base: dict, lora: dict, lora_cfg: LoRAConfig,
               dtype=None, base_module=transformer) -> dict:
    """base params + scale·A@B on each target; structure-preserving and
    shape-generic (stacked targets merge per stack entry — per expert for
    MoE). Family validation happens at init; `base_module` is accepted
    for API symmetry."""
    del base_module
    merged_layers = dict(base["layers"])
    for name, ab in lora["layers"].items():
        w = base["layers"][name]
        compute = jnp.dtype(dtype) if dtype is not None else w.dtype
        delta = jnp.einsum(
            "...ir,...ro->...io", ab["a"].astype(compute),
            ab["b"].astype(compute)) * lora_cfg.scale
        merged_layers[name] = (
            w + delta.reshape(w.shape).astype(w.dtype))
    out = dict(base)
    out["layers"] = merged_layers
    return out


def export_merged(params: dict, lora_cfg: LoRAConfig,
                  base_module=transformer) -> dict:
    """{"base","lora"} TrainState params -> plain servable base params."""
    return merge_lora(params["base"], params["lora"], lora_cfg,
                      base_module=base_module)


def make_lora_module(lora_cfg: LoRAConfig, base_module=transformer,
                     base_params: dict | None = None):
    """Build a loss-function module (same protocol as `models.transformer`)
    that trains only adapters.

    base_params: pretrained weights to adapt (the fine-tuning case). None
    random-inits the base — useful for tests and API symmetry only.

    The returned namespace provides `init_params`, `param_logical_axes`,
    `param_labels` (optimizer freeze mask) and `next_token_loss`, so it
    drops into `make_train_step` / `train_loop` / `Checkpointer` via their
    `loss_fn_module` argument — the same extension seam `models.moe` uses.
    """
    _target_table(base_module)  # raises for unknown module families

    class module:
        lora_config = lora_cfg

        @staticmethod
        def init_params(cfg: ModelConfig, rng: jax.Array) -> dict:
            rng_base, rng_lora = jax.random.split(rng)
            base = (base_params if base_params is not None
                    else base_module.init_params(cfg, rng_base))
            return {"base": base,
                    "lora": init_lora_params(cfg, lora_cfg, rng_lora,
                                             base_module)}

        @staticmethod
        def param_logical_axes(cfg: ModelConfig) -> dict:
            return {"base": base_module.param_logical_axes(cfg),
                    "lora": lora_logical_axes(cfg, lora_cfg, base_module)}

        @staticmethod
        def param_labels(cfg: ModelConfig) -> dict:
            """Optimizer labels: base frozen, adapters trained."""
            return {"base": jax.tree.map(lambda _: "frozen",
                                         base_module.param_logical_axes(cfg),
                                         is_leaf=lambda x: isinstance(x, tuple)),
                    "lora": jax.tree.map(
                        lambda _: "trainable",
                        lora_logical_axes(cfg, lora_cfg, base_module),
                        is_leaf=lambda x: isinstance(x, tuple))}

        @staticmethod
        def next_token_loss(params: dict, batch: dict, cfg: ModelConfig,
                            **kwargs):
            frozen = jax.tree.map(lax.stop_gradient, params["base"])
            merged = merge_lora(frozen, params["lora"], lora_cfg,
                                base_module=base_module)
            return base_module.next_token_loss(merged, batch, cfg, **kwargs)

    return module

"""Sparse Mixture-of-Experts decoder LM (Mixtral-style) with expert
parallelism.

TPU-first design: routing uses the GShard/Mesh-TF dense-dispatch algorithm —
top-k assignment becomes a (tokens, experts, capacity) one-hot dispatch
tensor contracted with two einsums. Everything is static-shaped, so XLA
tiles it onto the MXU, and the expert axis carries a sharding constraint
(`ep`) so XLA inserts the all-to-all for expert parallelism automatically.
No gather/scatter, no dynamic shapes, no host round-trips.

Attention/norms/rope are shared with the dense model; only the MLP is
replaced by the expert layer. Layers are stacked and scanned like
`models/transformer.py`; the router aux losses ride the scan carry.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.models import transformer
from cloud_server_tpu.ops import rms_norm, rope_table

Params = dict


# ---------------------------------------------------------------------------
# Routing (GShard dense dispatch)
# ---------------------------------------------------------------------------

def _capacity(cfg: ModelConfig, num_tokens: int) -> int:
    cap = int(math.ceil(cfg.expert_capacity_factor * num_tokens
                        * cfg.num_experts_per_token / cfg.num_experts))
    return max(cap, 4)


def top_k_routing(router_logits: jnp.ndarray, k: int, capacity: int):
    """Build dispatch/combine tensors from router logits.

    Args:
      router_logits: (T, E) float32.
      k: experts per token.
      capacity: per-expert buffer size C.

    Returns:
      dispatch: (T, E, C) bool-ish float — token t occupies slot c of
        expert e.
      combine: (T, E, C) float32 — dispatch weighted by the (renormalised)
        router probability.
      aux: dict with load-balance / z-loss ingredients.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)  # (T, E)

    # Top-k gating with renormalised weights.
    gate_vals, gate_idx = lax.top_k(probs, k)  # (T, k)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9)

    # One-hot per assignment: (T, k, E).
    assign = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)

    # Position of each assignment within its expert's buffer. Priority is
    # (k-slot, token-order): all primary assignments rank before secondary,
    # matching GShard. Flatten (k, T) so cumsum runs per expert.
    assign_kt = assign.transpose(1, 0, 2).reshape(k * t, e)  # (k*T, E)
    pos_kt = jnp.cumsum(assign_kt, axis=0) * assign_kt - 1.0  # slot index
    keep_kt = jnp.logical_and(pos_kt >= 0, pos_kt < capacity)
    pos = pos_kt.reshape(k, t, e).transpose(1, 0, 2)  # (T, k, E)
    keep = keep_kt.reshape(k, t, e).transpose(1, 0, 2)

    slot_onehot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32)  # (T,k,E,C)
    slot_onehot *= keep[..., None]
    dispatch = slot_onehot.sum(axis=1)  # (T, E, C)
    combine = (slot_onehot * gate_vals[:, :, None, None]).sum(axis=1)

    # Aux stats: fraction of tokens routed to each expert (top-1 view) and
    # mean router prob, per GShard load-balancing loss.
    frac_tokens = assign[:, 0, :].mean(axis=0)  # (E,)
    mean_probs = probs.mean(axis=0)  # (E,)
    aux = {
        "load_balance": (frac_tokens * mean_probs).sum() * e,
        "router_z": jnp.square(jax.nn.logsumexp(router_logits, -1)).mean(),
        "dropped_frac": 1.0 - keep[:, 0, :].sum() / t,
    }
    return dispatch, combine, aux


def moe_mlp(x: jnp.ndarray, lp: dict, cfg: ModelConfig):
    """Expert-parallel SwiGLU MoE layer.

    x: (B, S, D). lp: router (D, E), w_gate/w_up (E, D, F), w_down (E, F, D).
    Returns (out (B, S, D), aux dict of scalars).
    """
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    capacity = _capacity(cfg, b * s)

    router_logits = jnp.einsum(
        "td,de->te", tokens.astype(jnp.float32),
        lp["router"].astype(jnp.float32))
    dispatch, combine, aux = top_k_routing(
        router_logits, cfg.num_experts_per_token, capacity)

    # (T, E, C) x (T, D) -> (E, C, D): the all-to-all, inserted by XLA from
    # the `ep` sharding of the expert axis.
    xs = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype), tokens)
    gate = jnp.einsum("ecd,edf->ecf", xs, lp["w_gate"].astype(cfg.dtype))
    up = jnp.einsum("ecd,edf->ecf", xs, lp["w_up"].astype(cfg.dtype))
    act = jax.nn.silu(gate) * up
    ys = jnp.einsum("ecf,efd->ecd", act, lp["w_down"].astype(cfg.dtype))
    out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), ys)
    return out.reshape(b, s, d), aux


# ---------------------------------------------------------------------------
# Model: dense attention + MoE MLP blocks
# ---------------------------------------------------------------------------

def param_shapes(cfg: ModelConfig) -> dict[str, Any]:
    shapes = transformer.param_shapes(cfg)
    L, D, E, F = (cfg.num_layers, cfg.embed_dim, cfg.num_experts, cfg.mlp_dim)
    layers = shapes["layers"]
    for k in ("w_gate", "w_up", "w_down"):
        del layers[k]
    layers["router"] = (L, D, E)
    layers["w_gate"] = (L, E, D, F)
    layers["w_up"] = (L, E, D, F)
    layers["w_down"] = (L, E, F, D)
    return shapes


def param_logical_axes(cfg: ModelConfig) -> dict[str, Any]:
    axes = transformer.param_logical_axes(cfg)
    layers = axes["layers"]
    for k in ("w_gate", "w_up", "w_down"):
        del layers[k]
    layers["router"] = ("layers", "embed", None)
    layers["w_gate"] = ("layers", "experts", "embed", "expert_mlp")
    layers["w_up"] = ("layers", "experts", "embed", "expert_mlp")
    layers["w_down"] = ("layers", "experts", "expert_mlp", "embed")
    return axes


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    if cfg.num_experts < 2:
        raise ValueError("MoE model needs num_experts >= 2")
    dtype = jnp.dtype(cfg.param_dtype)
    shapes = param_shapes(cfg)
    paths, treedef = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple))
    keys = jax.random.split(rng, len(paths))
    fan_in = {"router": cfg.embed_dim, "w_gate": cfg.embed_dim,
              "w_up": cfg.embed_dim, "w_down": cfg.mlp_dim,
              "tokens": cfg.embed_dim, "kernel": cfg.embed_dim,
              "wq": cfg.embed_dim, "wk": cfg.embed_dim, "wv": cfg.embed_dim,
              "wo": cfg.num_heads * cfg.head_dim}
    out = []
    for (path, shape), key in zip(paths, keys):
        name = path[-1].key
        path_str = "/".join(p.key for p in path)
        if "norm" in path_str:
            out.append(jnp.ones(shape, dtype))
        else:
            std = 1.0 / math.sqrt(fan_in[name])
            out.append((jax.random.truncated_normal(
                key, -2.0, 2.0, shape, jnp.float32) * std).astype(dtype))
    return jax.tree.unflatten(treedef, out)


def moe_mlp_block(x, lp, cfg: ModelConfig):
    """Residual MoE MLP sub-block: norm -> route/experts -> add.

    The single definition shared by training (`_moe_block`) and the
    inference engine (`engine._mlp_apply`), so serve-time MoE math can
    never drift from the trained model.
    """
    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    out, aux = moe_mlp(h, lp, cfg)
    return x + out, aux


def _moe_block(x, lp, cfg: ModelConfig, cos, sin, attn_fn, positions=None):
    x = transformer._attention_block(x, lp, cfg, cos, sin, attn_fn, positions)
    return moe_mlp_block(x, lp, cfg)


def forward_hidden(params: Params, tokens: jnp.ndarray, cfg: ModelConfig,
                   segment_ids: jnp.ndarray | None = None):
    """(B, S) -> (final-normed hidden (B, S, D), aux dict of router stats).

    segment_ids: optional packed-sequence ids — same block-diagonal
    attention + per-document RoPE semantics as the dense family
    (transformer.forward_hidden)."""
    cos, sin = rope_table(cfg, tokens.shape[1])
    # Unshard the table's embed dim BEFORE the lookup: a tp-sharded D at
    # the gather makes XLA produce a D-sharded (B, S, D) it must then
    # replicate-and-repartition to the batch/sequence layout ("Involuntary
    # full rematerialization" in the SPMD partitioner). One table
    # all-gather per forward is strictly cheaper.
    table = transformer.constrain(params["embed"]["tokens"].astype(cfg.dtype),
                      ("vocab", None))
    x = table[tokens]
    x = transformer.constrain(x, ("batch", "sequence", None))
    positions = None
    if segment_ids is not None:
        from cloud_server_tpu.ops.segments import positions_from_segments
        positions = positions_from_segments(segment_ids)
        attn_fn = transformer._packed_attention_fn(cfg, segment_ids)
    else:
        attn_fn = transformer._get_attention_fn(cfg)

    block = partial(_moe_block, cfg=cfg, cos=cos, sin=sin, attn_fn=attn_fn,
                    positions=positions)
    block = transformer.apply_remat(block, cfg)

    def scan_body(carry, lp):
        x, lb, rz, dropped = carry
        x, aux = block(x, lp)
        return (x, lb + aux["load_balance"], rz + aux["router_z"],
                dropped + aux["dropped_frac"]), None

    zero = jnp.zeros((), jnp.float32)
    (x, lb, rz, dropped), _ = lax.scan(
        scan_body, (x, zero, zero, zero), params["layers"])
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    n = cfg.num_layers
    aux = {"load_balance": lb / n, "router_z": rz / n, "dropped_frac": dropped / n}
    return x, aux


def forward(params: Params, tokens: jnp.ndarray, cfg: ModelConfig,
            segment_ids: jnp.ndarray | None = None):
    """(B, S) -> (logits (B, S, V) f32, aux dict of scalar router stats)."""
    x, aux = forward_hidden(params, tokens, cfg, segment_ids)
    return transformer.unembed(x, params, cfg), aux


def next_token_loss(params: Params, batch: dict, cfg: ModelConfig,
                    z_loss_coef: float = 0.0, aux_loss_coef: float = 0.01,
                    router_z_coef: float = 0.0):
    seg = batch.get("segment_ids")
    batch = transformer.apply_segment_loss_mask(batch)
    if cfg.ce_impl == "pallas" or cfg.vocab_chunk > 0:
        x, aux = forward_hidden(params, batch["tokens"], cfg, segment_ids=seg)
        loss, metrics = transformer.hidden_state_loss(
            x, params, batch, cfg, z_loss_coef)
    else:
        logits, aux = forward(params, batch["tokens"], cfg, segment_ids=seg)
        loss, metrics = transformer.masked_cross_entropy(
            logits, batch, z_loss_coef)
    metrics.update(load_balance=aux["load_balance"],
                   router_z=aux["router_z"],
                   dropped_frac=aux["dropped_frac"])
    loss = loss + aux_loss_coef * aux["load_balance"]
    if router_z_coef > 0.0:
        loss = loss + router_z_coef * aux["router_z"]
    return loss, metrics

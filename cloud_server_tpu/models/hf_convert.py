"""HuggingFace LLaMA-family checkpoint interop.

Lets a user bring existing weights to this framework (and take ours back
out): `LlamaForCausalLM`-style state dicts convert losslessly to/from our
parameter tree. The RoPE convention matches (both use the half-split
"rotate_half" layout and the same theta schedule), so conversion is pure
reshaping/transposition — verified to logits parity against the
`transformers` reference implementation in tests/test_hf_convert.py.

Layout mapping (HF `nn.Linear.weight` is (out, in); ours are (in, out)-
style einsum operands):

  model.embed_tokens.weight (V, D)      -> embed.tokens (V, D)
  layers.i.self_attn.q_proj (H*Dh, D)   -> wq[i] (D, H, Dh)    (T + reshape)
  layers.i.self_attn.k_proj (KH*Dh, D)  -> wk[i] (D, KH, Dh)
  layers.i.self_attn.v_proj (KH*Dh, D)  -> wv[i] (D, KH, Dh)
  layers.i.self_attn.o_proj (D, H*Dh)   -> wo[i] (H, Dh, D)
  layers.i.mlp.gate_proj (F, D)         -> w_gate[i] (D, F)
  layers.i.mlp.up_proj (F, D)           -> w_up[i] (D, F)
  layers.i.mlp.down_proj (D, F)         -> w_down[i] (F, D)
  layers.i.input_layernorm (D,)         -> attn_norm[i]
  layers.i.post_attention_layernorm (D,)-> mlp_norm[i]
  model.norm.weight (D,)                -> final_norm.scale
  lm_head.weight (V, D)                 -> lm_head.kernel (D, V)
                                           (absent when tie_word_embeddings)

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); checkpoint interop is part of the re-scoped build inventory.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from cloud_server_tpu.config import ModelConfig


def config_from_hf(hf_config: Any, **overrides) -> ModelConfig:
    """Build a ModelConfig from a transformers LlamaConfig-like object."""
    fields = dict(
        vocab_size=hf_config.vocab_size,
        embed_dim=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim", None)
        or hf_config.hidden_size // hf_config.num_attention_heads,
        mlp_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
    )
    fields.update(overrides)
    return ModelConfig(**fields)


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def params_from_hf(state_dict: Mapping[str, Any], cfg: ModelConfig,
                   dtype: str | None = None) -> dict:
    """Convert an HF LlamaForCausalLM state dict to this framework's
    parameter tree (leaves in `dtype`, default cfg.param_dtype).

    Conversion is per-key lazy: each tensor is pulled from the (possibly
    torch, possibly bf16) state dict and converted on use, so peak host
    memory stays near one extra copy rather than a full f32 duplicate of
    the checkpoint."""
    L, D, H, KH, Dh = (cfg.num_layers, cfg.embed_dim, cfg.num_heads,
                       cfg.num_kv_heads, cfg.head_dim)
    out_dtype = jnp.dtype(dtype or cfg.param_dtype)

    def get(key: str) -> np.ndarray:
        return _np(state_dict[key])

    def stack(fmt: str) -> np.ndarray:
        return np.stack([get(fmt.format(i)) for i in range(L)])

    wq = stack("model.layers.{}.self_attn.q_proj.weight")  # (L, H*Dh, D)
    wk = stack("model.layers.{}.self_attn.k_proj.weight")
    wv = stack("model.layers.{}.self_attn.v_proj.weight")
    wo = stack("model.layers.{}.self_attn.o_proj.weight")  # (L, D, H*Dh)

    params = {
        "embed": {"tokens": jnp.asarray(
            get("model.embed_tokens.weight"), out_dtype)},
        "layers": {
            "attn_norm": jnp.asarray(
                stack("model.layers.{}.input_layernorm.weight"), out_dtype),
            "mlp_norm": jnp.asarray(
                stack("model.layers.{}.post_attention_layernorm.weight"),
                out_dtype),
            "wq": jnp.asarray(
                wq.transpose(0, 2, 1).reshape(L, D, H, Dh), out_dtype),
            "wk": jnp.asarray(
                wk.transpose(0, 2, 1).reshape(L, D, KH, Dh), out_dtype),
            "wv": jnp.asarray(
                wv.transpose(0, 2, 1).reshape(L, D, KH, Dh), out_dtype),
            "wo": jnp.asarray(
                wo.transpose(0, 2, 1).reshape(L, H, Dh, D), out_dtype),
            "w_gate": jnp.asarray(
                stack("model.layers.{}.mlp.gate_proj.weight"
                      ).transpose(0, 2, 1), out_dtype),
            "w_up": jnp.asarray(
                stack("model.layers.{}.mlp.up_proj.weight"
                      ).transpose(0, 2, 1), out_dtype),
            "w_down": jnp.asarray(
                stack("model.layers.{}.mlp.down_proj.weight"
                      ).transpose(0, 2, 1), out_dtype),
        },
        "final_norm": {"scale": jnp.asarray(
            get("model.norm.weight"), out_dtype)},
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" not in state_dict:
            raise ValueError(
                "state dict has no lm_head.weight but cfg.tie_embeddings "
                "is False — pass a config with tie_embeddings=True")
        params["lm_head"] = {"kernel": jnp.asarray(
            get("lm_head.weight").T, out_dtype)}
    return params


def params_to_hf(params: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Inverse of `params_from_hf`: our tree -> HF state-dict numpy arrays
    (torch-free; wrap with torch.from_numpy for transformers)."""
    L, D, H, KH, Dh = (cfg.num_layers, cfg.embed_dim, cfg.num_heads,
                       cfg.num_kv_heads, cfg.head_dim)
    lp = params["layers"]
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["embed"]["tokens"], np.float32),
        "model.norm.weight": np.asarray(
            params["final_norm"]["scale"], np.float32),
    }
    for i in range(L):
        pre = f"model.layers.{i}"
        sd[f"{pre}.input_layernorm.weight"] = np.asarray(
            lp["attn_norm"][i], np.float32)
        sd[f"{pre}.post_attention_layernorm.weight"] = np.asarray(
            lp["mlp_norm"][i], np.float32)
        sd[f"{pre}.self_attn.q_proj.weight"] = np.asarray(
            lp["wq"][i], np.float32).reshape(D, H * Dh).T
        sd[f"{pre}.self_attn.k_proj.weight"] = np.asarray(
            lp["wk"][i], np.float32).reshape(D, KH * Dh).T
        sd[f"{pre}.self_attn.v_proj.weight"] = np.asarray(
            lp["wv"][i], np.float32).reshape(D, KH * Dh).T
        sd[f"{pre}.self_attn.o_proj.weight"] = np.asarray(
            lp["wo"][i], np.float32).reshape(H * Dh, D).T
        sd[f"{pre}.mlp.gate_proj.weight"] = np.asarray(
            lp["w_gate"][i], np.float32).T
        sd[f"{pre}.mlp.up_proj.weight"] = np.asarray(
            lp["w_up"][i], np.float32).T
        sd[f"{pre}.mlp.down_proj.weight"] = np.asarray(
            lp["w_down"][i], np.float32).T
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np.asarray(
            params["lm_head"]["kernel"], np.float32).T
    return sd


def load_hf_checkpoint(path: str,
                       **config_overrides) -> tuple[ModelConfig, dict]:
    """Load a local HF LLaMA-family checkpoint directory: returns
    (ModelConfig, params). Requires `transformers` + `torch` (CPU).

    `config_overrides` go to ModelConfig (e.g. dtype="float32",
    attention_impl="flash"); parameter leaves follow the resulting
    cfg.param_dtype. The torch model loads in its checkpoint dtype
    (torch_dtype="auto"), not f32, to halve peak host memory."""
    import transformers

    model = transformers.AutoModelForCausalLM.from_pretrained(
        path, torch_dtype="auto")
    cfg = config_from_hf(model.config, **config_overrides)
    return cfg, params_from_hf(model.state_dict(), cfg)

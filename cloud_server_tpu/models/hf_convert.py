"""HuggingFace LLaMA-family checkpoint interop.

Lets a user bring existing weights to this framework (and take ours back
out): `LlamaForCausalLM`-style state dicts convert losslessly to/from our
parameter tree. The RoPE convention matches (both use the half-split
"rotate_half" layout and the same theta schedule), so conversion is pure
reshaping/transposition — verified to logits parity against the
`transformers` reference implementation in tests/test_hf_convert.py.

Layout mapping (HF `nn.Linear.weight` is (out, in); ours are (in, out)-
style einsum operands):

  model.embed_tokens.weight (V, D)      -> embed.tokens (V, D)
  layers.i.self_attn.q_proj (H*Dh, D)   -> wq[i] (D, H, Dh)    (T + reshape)
  layers.i.self_attn.k_proj (KH*Dh, D)  -> wk[i] (D, KH, Dh)
  layers.i.self_attn.v_proj (KH*Dh, D)  -> wv[i] (D, KH, Dh)
  layers.i.self_attn.o_proj (D, H*Dh)   -> wo[i] (H, Dh, D)
  layers.i.mlp.gate_proj (F, D)         -> w_gate[i] (D, F)
  layers.i.mlp.up_proj (F, D)           -> w_up[i] (D, F)
  layers.i.mlp.down_proj (D, F)         -> w_down[i] (F, D)
  layers.i.input_layernorm (D,)         -> attn_norm[i]
  layers.i.post_attention_layernorm (D,)-> mlp_norm[i]
  model.norm.weight (D,)                -> final_norm.scale
  lm_head.weight (V, D)                 -> lm_head.kernel (D, V)
                                           (absent when tie_word_embeddings)

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); checkpoint interop is part of the re-scoped build inventory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from cloud_server_tpu.config import ModelConfig


# Overriding these changes the parameter-tree shapes / semantics and can
# only corrupt a conversion, so config_from_hf rejects them rather than
# forwarding them into a reshape error deep inside params_from_hf.
_STRUCTURAL_FIELDS = frozenset({
    "vocab_size", "embed_dim", "num_layers", "num_heads", "num_kv_heads",
    "head_dim", "mlp_dim", "tie_embeddings", "num_experts",
    "rope_theta", "rope_scaling", "rope_scaling_factor",
    "rope_low_freq_factor", "rope_high_freq_factor", "rope_original_max_len",
})


def _rope_fields_from_hf(hf_config: Any) -> dict:
    """Map transformers' rope_scaling dict onto ModelConfig rope fields.

    Supported: absent/default (no scaling), "linear", "llama3". Anything
    else (yarn, dynamic, longrope...) raises — silently dropping the
    schedule would serve wrong logits at every position."""
    rs = getattr(hf_config, "rope_scaling", None)
    if not rs:
        return {}
    kind = rs.get("rope_type", rs.get("type", "default"))
    if kind in (None, "default"):
        return {}
    if kind == "linear":
        return dict(rope_scaling="linear",
                    rope_scaling_factor=float(rs["factor"]))
    if kind == "llama3":
        return dict(
            rope_scaling="llama3",
            rope_scaling_factor=float(rs["factor"]),
            rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
            rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
            rope_original_max_len=int(
                rs.get("original_max_position_embeddings", 8192)))
    raise ValueError(
        f"unsupported rope_scaling type {kind!r} in HF config — supported: "
        "default/linear/llama3")


def config_from_hf(hf_config: Any, **overrides) -> ModelConfig:
    """Build a ModelConfig from a transformers LlamaConfig-like object.

    `overrides` may adjust behavioral fields (dtype, attention_impl,
    remat, max_seq_len, ...); structural fields that must match the
    checkpoint tensors are rejected when they contradict the HF config.
    Unsupported architecture variants (non-SiLU activation, attention/MLP
    biases, exotic rope scaling) raise instead of converting silently
    wrong."""
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise ValueError(f"unsupported hidden_act {act!r} (SwiGLU/SiLU only)")
    for bias_field in ("attention_bias", "mlp_bias"):
        if getattr(hf_config, bias_field, False):
            raise ValueError(
                f"unsupported {bias_field}=True — this framework's "
                "LLaMA-family layers are bias-free")
    fields = dict(
        vocab_size=hf_config.vocab_size,
        embed_dim=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads",
                             hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim", None)
        or hf_config.hidden_size // hf_config.num_attention_heads,
        mlp_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
        **_rope_fields_from_hf(hf_config),
    )
    # Structural fields the HF config doesn't mention still have a correct
    # value for this checkpoint: the ModelConfig default (dense model, no
    # rope scaling). Seed those so every structural override is compared
    # against SOMETHING — `fields.get(key, val)` would vacuously accept
    # e.g. num_experts=8 on a dense checkpoint.
    defaults = {f.name: f.default for f in dataclasses.fields(ModelConfig)}
    for key, val in overrides.items():
        if key in _STRUCTURAL_FIELDS and val != fields.get(key, defaults[key]):
            raise ValueError(
                f"config override {key}={val!r} contradicts the checkpoint "
                f"({fields.get(key, defaults[key])!r}) — structural fields "
                "come from the HF config; drop the override")
    fields.update(overrides)
    return ModelConfig(**fields)


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


# State-dict keys that are buffers/bookkeeping, not weights — safe to skip.
_IGNORABLE_KEY_PARTS = ("rotary_emb", "position_ids", "masked_bias",
                        "attn.bias")


def params_from_hf(state_dict: Mapping[str, Any], cfg: ModelConfig,
                   dtype: str | None = None) -> dict:
    """Convert an HF LlamaForCausalLM state dict to this framework's
    parameter tree (leaves in `dtype`, default cfg.param_dtype).

    Conversion runs one stacked tensor family at a time — each per-layer
    stack is built, transposed, converted to a jnp leaf and its f32 numpy
    intermediate freed before the next family starts — so peak host
    memory is the source checkpoint + the growing output tree + ONE
    f32 layer stack, not four attention stacks at once.

    Every state-dict key must either be consumed or match a known
    ignorable buffer pattern; leftovers (e.g. attention biases from a
    checkpoint with attention_bias=True) raise instead of being silently
    dropped."""
    L, D, H, KH, Dh = (cfg.num_layers, cfg.embed_dim, cfg.num_heads,
                       cfg.num_kv_heads, cfg.head_dim)
    out_dtype = jnp.dtype(dtype or cfg.param_dtype)
    consumed: set[str] = set()

    def get(key: str) -> np.ndarray:
        consumed.add(key)
        return _np(state_dict[key])

    def stack(fmt: str, transform=None) -> jnp.ndarray:
        arr = np.stack([get(fmt.format(i)) for i in range(L)])
        if transform is not None:
            arr = transform(arr)
        return jnp.asarray(arr, out_dtype)

    layers = {
        "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
        "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight"),
        # HF projections are (out, in); transpose then split the head dims.
        "wq": stack("model.layers.{}.self_attn.q_proj.weight",
                    lambda a: a.transpose(0, 2, 1).reshape(L, D, H, Dh)),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight",
                    lambda a: a.transpose(0, 2, 1).reshape(L, D, KH, Dh)),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight",
                    lambda a: a.transpose(0, 2, 1).reshape(L, D, KH, Dh)),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight",
                    lambda a: a.transpose(0, 2, 1).reshape(L, H, Dh, D)),
        "w_gate": stack("model.layers.{}.mlp.gate_proj.weight",
                        lambda a: a.transpose(0, 2, 1)),
        "w_up": stack("model.layers.{}.mlp.up_proj.weight",
                      lambda a: a.transpose(0, 2, 1)),
        "w_down": stack("model.layers.{}.mlp.down_proj.weight",
                        lambda a: a.transpose(0, 2, 1)),
    }
    params = {
        "embed": {"tokens": jnp.asarray(
            get("model.embed_tokens.weight"), out_dtype)},
        "layers": layers,
        "final_norm": {"scale": jnp.asarray(
            get("model.norm.weight"), out_dtype)},
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" not in state_dict:
            raise ValueError(
                "state dict has no lm_head.weight but cfg.tie_embeddings "
                "is False — pass a config with tie_embeddings=True")
        params["lm_head"] = {"kernel": jnp.asarray(
            get("lm_head.weight").T, out_dtype)}
    else:
        consumed.add("lm_head.weight")  # alias of the embedding when tied

    leftover = sorted(
        k for k in state_dict
        if k not in consumed
        and not any(part in k for part in _IGNORABLE_KEY_PARTS))
    if leftover:
        preview = ", ".join(leftover[:6])
        raise ValueError(
            f"{len(leftover)} unsupported weight(s) in checkpoint would be "
            f"silently dropped: {preview}"
            + (" ..." if len(leftover) > 6 else "")
            + " — this architecture variant (biases?) is not supported")
    return params


def params_to_hf(params: Mapping[str, Any], cfg: ModelConfig) -> dict:
    """Inverse of `params_from_hf`: our tree -> HF state-dict numpy arrays
    (torch-free; wrap with torch.from_numpy for transformers)."""
    L, D, H, KH, Dh = (cfg.num_layers, cfg.embed_dim, cfg.num_heads,
                       cfg.num_kv_heads, cfg.head_dim)
    lp = params["layers"]
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["embed"]["tokens"], np.float32),
        "model.norm.weight": np.asarray(
            params["final_norm"]["scale"], np.float32),
    }
    for i in range(L):
        pre = f"model.layers.{i}"
        sd[f"{pre}.input_layernorm.weight"] = np.asarray(
            lp["attn_norm"][i], np.float32)
        sd[f"{pre}.post_attention_layernorm.weight"] = np.asarray(
            lp["mlp_norm"][i], np.float32)
        sd[f"{pre}.self_attn.q_proj.weight"] = np.asarray(
            lp["wq"][i], np.float32).reshape(D, H * Dh).T
        sd[f"{pre}.self_attn.k_proj.weight"] = np.asarray(
            lp["wk"][i], np.float32).reshape(D, KH * Dh).T
        sd[f"{pre}.self_attn.v_proj.weight"] = np.asarray(
            lp["wv"][i], np.float32).reshape(D, KH * Dh).T
        sd[f"{pre}.self_attn.o_proj.weight"] = np.asarray(
            lp["wo"][i], np.float32).reshape(H * Dh, D).T
        sd[f"{pre}.mlp.gate_proj.weight"] = np.asarray(
            lp["w_gate"][i], np.float32).T
        sd[f"{pre}.mlp.up_proj.weight"] = np.asarray(
            lp["w_up"][i], np.float32).T
        sd[f"{pre}.mlp.down_proj.weight"] = np.asarray(
            lp["w_down"][i], np.float32).T
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np.asarray(
            params["lm_head"]["kernel"], np.float32).T
    return sd


def load_hf_checkpoint(path: str,
                       **config_overrides) -> tuple[ModelConfig, dict]:
    """Load a local HF LLaMA-family checkpoint directory: returns
    (ModelConfig, params). Requires `transformers` + `torch` (CPU).

    `config_overrides` go to ModelConfig (e.g. dtype="float32",
    attention_impl="flash"); parameter leaves follow the resulting
    cfg.param_dtype. The torch model loads in its checkpoint dtype
    (torch_dtype="auto"), not f32, to halve peak host memory."""
    import transformers

    model = transformers.AutoModelForCausalLM.from_pretrained(
        path, torch_dtype="auto")
    cfg = config_from_hf(model.config, **config_overrides)
    return cfg, params_from_hf(model.state_dict(), cfg)

"""Paged continuous-batching server: block-table KV, shared prefixes,
chunked prefill, and in-server speculative decoding.

This is the successor of `inference.server.InferenceServer` (which keeps
the contiguous slot cache). What the paged design buys:

  * Memory scales with resident tokens, not max_slots x max_len: the pool
    is `num_pages` fixed-size pages; a slot holds ceil(context / ps)
    pages. More concurrent requests fit in the same HBM whenever requests
    are shorter than max_context or share prefixes.
  * Prefix reuse is GENERAL (radix-style, page granularity): any request
    whose token prefix matches cached pages — same system prompt, same
    few-shot header, a multi-turn follow-up replaying the conversation
    (generated tokens included) — skips prefill for the shared pages.
    No server-lifetime single prefix; the cache is learned from traffic
    and LRU-evicted under memory pressure (inference/block_allocator.py).
  * Chunked prefill: admissions run as a sequence of bounded window
    dispatches (`prefill_chunk` tokens each) interleaved with decode
    steps, so one long prompt never stalls active decodes for its whole
    prefill — inter-token latency stays bounded (the serving bench
    measures it).
  * Speculative decoding IS the decode loop (spec_drafts > 0): per-slot
    n-gram proposals drafted on device from each slot's token history,
    verified batch-wide in one W = drafts+1 window, committed per slot
    with the exact accept/residual rule (`speculative._accept_point_mass`
    — output distribution provably unchanged; token-for-token greedy).
    No draft model, no extra memory; repetition-heavy decodes commit
    several tokens per model pass.

Scheduling state is HOST-authoritative (tables, lengths, active,
last_token live in numpy and ride into each dispatch as small inputs);
the device owns only the big buffers (page pools + per-slot token
history), donated through every dispatch. One device_get per scheduler
iteration, amortised over `decode_chunk` (speculative) rounds
(multi-token scheduling, as in the contiguous server).

Write-safety rules the scheduler maintains (see paged_engine for why
writes through sentinel tables drop):
  * decode dispatches get SENTINEL table rows for every non-live slot, so
    a slot mid-admission can never have its freshly prefilled pages
    clobbered by the concurrent batch-wide decode window;
  * page chains are fully reserved at admission (prompt + max_new +
    window slack), so decode never outgrows its chain and there is no
    mid-flight OOM/preemption path.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference import paged_engine
from cloud_server_tpu.inference.block_allocator import BlockAllocator
from cloud_server_tpu.inference.sampling import sample_logits, sampling_probs
from cloud_server_tpu.inference.server import (
    Request, _bucket, _token_logprobs)
from cloud_server_tpu.inference.speculative import (
    _accept_point_mass, _ngram_drafts)


def _pow2_buckets(lo: int, hi: int) -> list[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    return out + [hi]


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# Jitted dispatches (module-level so compiles are shared across servers)
# ---------------------------------------------------------------------------


def _make_cache(pools, lengths, tables):
    return paged_engine.PagedKVCache(
        k=pools["k"], v=pools["v"], lengths=lengths, tables=tables,
        k_scale=pools.get("k_scale"), v_scale=pools.get("v_scale"))


def _split_cache(cache):
    pools = {"k": cache.k, "v": cache.v}
    if cache.k_scale is not None:
        pools["k_scale"] = cache.k_scale
        pools["v_scale"] = cache.v_scale
    return pools


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "scatter_prompt", "mesh"),
         donate_argnums=(1,))
def _prefill_chunk(params, state, chunk, g_lens, g_tables, sample_at,
                   slot_ids, prompt_rows, prompt_lens, rng, *,
                   cfg: ModelConfig, infer_cfg: InferConfig,
                   scatter_prompt: bool, mesh=None):
    """One admission chunk for a (padded) G-row group.

    chunk: (G, Wc) tokens for positions [g_lens, g_lens + Wc) per row —
    rows at different offsets, which is how shared prefixes resume deeper
    and how successive chunks continue. sample_at: in-window index of
    each row's LAST true prompt token (clamped; the caller keeps the
    sample only when it truly falls inside this chunk). On the first
    chunk (`scatter_prompt`) each row's full prompt is written into its
    slot's device history for n-gram drafting. Padding rows carry
    slot_id == max_slots and sentinel tables: every scatter drops.

    Returns (state', first-token candidates (G,), their logprobs (G,)).
    """
    cache = _make_cache(state["pools"], g_lens, g_tables)
    logits, cache = paged_engine.window_forward(
        params, chunk, cfg, cache, logits_at=sample_at, mesh=mesh)
    toks = sample_logits(logits, rng, infer_cfg)
    lps = _token_logprobs(logits, toks)
    hist = state["hist"]
    if scatter_prompt:
        pb = prompt_rows.shape[1]
        cols = jnp.broadcast_to(jnp.arange(pb)[None, :], prompt_rows.shape)
        cols = jnp.where(cols < prompt_lens[:, None], cols, hist.shape[1])
        hist = hist.at[slot_ids[:, None], cols].set(prompt_rows,
                                                    mode="drop")
    return {"pools": _split_cache(cache), "hist": hist}, toks, lps


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "n_rounds", "mesh"),
         donate_argnums=(1,))
def _decode_rounds(params, state, lengths, tables, last_token, live,
                   rng, *, cfg: ModelConfig, infer_cfg: InferConfig,
                   n_rounds: int, mesh=None):
    """n_rounds plain decode steps (W=1) in one dispatch (lax.scan).

    `live` slots advance one token per round; the rest are frozen (their
    writes drop through the sentinel tables the caller passes).

    Returns (state', lengths', last', (toks (R, B), lps (R, B),
    counts (R, B) int32)).
    """
    pad = infer_cfg.pad_token_id
    batch_idx = jnp.arange(lengths.shape[0])

    def body(carry, rng_t):
        lengths, last, hist, pools = carry
        # `last` is the committed token at sequence position `lengths`
        # (this round writes its kv there); record it in the history so
        # drafting/multi-turn reads see an unbroken token sequence
        cols = jnp.where(live, lengths, hist.shape[1])
        hist = hist.at[batch_idx, cols].set(last, mode="drop")
        cache = _make_cache(pools, lengths, tables)
        logits, cache = paged_engine.window_forward(
            params, last[:, None], cfg, cache,
            logits_at=jnp.zeros_like(lengths), mesh=mesh)
        tok = sample_logits(logits, rng_t, infer_cfg)
        lp = _token_logprobs(logits, tok)
        tok = jnp.where(live, tok, pad)
        new_len = jnp.where(live, lengths + 1, lengths)
        last = jnp.where(live, tok, last)
        return ((new_len, last, hist, _split_cache(cache)),
                (tok, lp, live.astype(jnp.int32)))

    (lengths, last, hist, pools), out = lax.scan(
        body, (lengths, last_token, state["hist"], state["pools"]),
        jax.random.split(rng, n_rounds))
    return {"pools": pools, "hist": hist}, lengths, last, out


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "n_rounds", "n_drafts",
                          "mesh"),
         donate_argnums=(1,))
def _spec_rounds(params, state, lengths, tables, last_token, live,
                 stop_len, rng, *, cfg: ModelConfig, infer_cfg: InferConfig,
                 n_rounds: int, n_drafts: int, mesh=None):
    """n_rounds speculative rounds in one dispatch.

    Each round drafts `n_drafts` tokens per slot from its device-resident
    history (prompt-lookup n-grams), scores the (drafts+1)-token window in
    ONE batched window_forward, and commits each slot's accepted prefix
    plus the corrective/bonus token (exact accept rule). Commits are
    capped at stop_len so a slot never outruns its page chain.

    Returns (state', lengths', last',
    (toks (R, B, G+1), lps (R, B, G+1), counts (R, B))).
    """
    g = n_drafts
    b = lengths.shape[0]
    pad = infer_cfg.pad_token_id
    batch_idx = jnp.arange(b)
    j = jnp.arange(g + 1)[None, :]

    def body(carry, rng_t):
        lengths, last, hist, pools = carry
        rng_acc, _ = jax.random.split(rng_t)
        can_commit = live & (lengths < stop_len)

        # `last` is the committed token at sequence position `lengths`;
        # write it into the history BEFORE drafting so bigram lookups
        # spanning the prompt/generated boundary see the true sequence
        cols_last = jnp.where(live, lengths, hist.shape[1])
        hist = hist.at[batch_idx, cols_last].set(last, mode="drop")
        valid = lengths + 1  # committed tokens = [0, lengths] incl. last
        t_prev2 = hist[batch_idx, jnp.maximum(valid - 2, 0)]
        drafts = _ngram_drafts(hist, valid, t_prev2, last, g, pad)
        window = jnp.concatenate([last[:, None], drafts], axis=1)

        cache = _make_cache(pools, lengths, tables)
        vlogits, cache = paged_engine.window_forward(
            params, window, cfg, cache, logits_at=None, all_logits=True,
            mesh=mesh)
        p_probs = sampling_probs(vlogits, infer_cfg)  # (B, G+1, V)
        n_acc, x = _accept_point_mass(drafts, p_probs, rng_acc)

        drafts_x = jnp.concatenate([drafts, x[:, None]], axis=1)
        committed = jnp.where(j < n_acc[:, None], drafts_x,
                              jnp.where(j == n_acc[:, None],
                                        x[:, None], pad))
        count = jnp.where(can_commit, n_acc + 1, 0)
        count = jnp.minimum(count, jnp.maximum(stop_len - lengths, 0))
        toks = jnp.where(j < count[:, None], committed, pad)
        # log P(tok) under the raw target distribution at each window
        # position (position i's logits score the token committed there)
        lps = jnp.take_along_axis(
            jax.nn.log_softmax(vlogits, axis=-1),
            jnp.maximum(toks, 0)[..., None], axis=-1)[..., 0]

        new_len = lengths + count
        # committed[j] is the token at sequence position lengths + 1 + j
        # (position `lengths` holds `last`, written above)
        cols = (lengths + 1)[:, None] + j
        cols = jnp.where(j < count[:, None], cols, hist.shape[1])
        hist = hist.at[batch_idx[:, None], cols].set(toks, mode="drop")
        last_idx = jnp.maximum(count - 1, 0)
        last2 = jnp.where(count > 0, committed[batch_idx, last_idx], last)
        return ((new_len, last2, hist, _split_cache(cache)),
                (toks, lps, count))

    (lengths, last, hist, pools), out = lax.scan(
        body, (lengths, last_token, state["hist"], state["pools"]),
        jax.random.split(rng, n_rounds))
    return {"pools": pools, "hist": hist}, lengths, last, out


# ---------------------------------------------------------------------------
# Host-side scheduler
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Slot:
    req: Request
    prompt: list[int]
    pages: list[int]            # full chain, shared prefix first
    shared_len: int
    stop_len: int               # prompt + max_new (absolute positions)


@dataclasses.dataclass
class _AdmitJob:
    """An in-flight chunked admission: one bucketed group of slots."""

    slots: list[int]
    chunk_w: int
    n_chunks: int
    rows: np.ndarray               # (G, n_chunks*chunk_w) remainder tokens
    rem_lens: np.ndarray           # (G,) true remainder lengths
    base_lens: np.ndarray          # (G,) shared_len per row
    prompt_rows: np.ndarray        # (G, prompt_bucket)
    prompt_lens: np.ndarray        # (G,)
    toks: np.ndarray               # captured first-token candidates
    lps: np.ndarray
    got: np.ndarray                # bool — sample captured yet
    next_chunk: int = 0


class PagedInferenceServer:
    """Continuous-batching server over the paged KV cache.

    Same client API as `InferenceServer` (submit / generate / step /
    start / stop / run_until_idle); see the module docstring for what
    changes inside.
    """

    def __init__(self, params, cfg: ModelConfig, infer_cfg: InferConfig, *,
                 max_slots: int = 8, max_context: int = 1024,
                 page_size: int = 128, num_pages: int | None = None,
                 prompt_buckets: Sequence[int] | None = None,
                 decode_chunk: int = 8, spec_drafts: int = 0,
                 prefill_chunk: int = 256, seed: int = 0,
                 mesh=None, tp_axis: str = "tp"):
        from cloud_server_tpu.models.quantization import QTensor
        target = jnp.dtype(cfg.dtype)

        def cast_leaf(w):
            if isinstance(w, QTensor):
                return w
            if getattr(w, "dtype", None) == jnp.float32 and w.ndim >= 1:
                return w.astype(target)
            return w

        self.params = jax.tree.map(
            cast_leaf, params, is_leaf=lambda x: isinstance(x, QTensor))
        self.cfg = cfg
        self.infer_cfg = infer_cfg
        self.max_slots = max_slots
        self.page_size = page_size
        self.spec_drafts = spec_drafts
        self.decode_chunk = max(1, decode_chunk)
        self.window = spec_drafts + 1  # kv slack per decode round
        if max_context % page_size:
            raise ValueError(f"{max_context=} must be a multiple of "
                             f"{page_size=}")
        if (cfg.decode_attention_impl == "pallas"
                and jax.default_backend() == "tpu" and page_size % 128):
            # fail at construction, not at the first dispatch — the TPU
            # kernel's manual-DMA slices tile the minor dim by 128
            raise ValueError(
                f"page_size={page_size} must be a multiple of 128 for the "
                "pallas decode path on TPU")
        self.max_context = max_context
        self.max_pages_per_slot = max_context // page_size
        if num_pages is None:
            # default: the same HBM the contiguous layout would reserve
            num_pages = max_slots * self.max_pages_per_slot
        self.allocator = BlockAllocator(num_pages, page_size)
        self.prefill_chunk = max(page_size, min(prefill_chunk, max_context))
        if self.prefill_chunk % page_size:
            raise ValueError("prefill_chunk must be a page multiple")
        if prompt_buckets is None:
            prompt_buckets = _pow2_buckets(16, max_context)
        self.prompt_buckets = sorted(prompt_buckets)
        # remainders bucket to a pow2 <= prefill_chunk (single-chunk jobs)
        # or a prefill_chunk multiple (multi-chunk jobs) — chunk WIDTHS
        # stay a small fixed set, chunk COUNTS are host-side loops
        self._rem_buckets = _pow2_buckets(16, self.prefill_chunk)

        # Tensor-parallel serving: the XLA side needs only the params'
        # NamedShardings (jit propagates). The mesh is kept for two
        # things — sharding the page pools on their kv-head axis so the
        # layout is intentional rather than inferred, and running the
        # pallas kernel under shard_map (it cannot be auto-partitioned).
        self.mesh = mesh
        self.tp_axis = tp_axis
        tp = 1 if mesh is None else int(mesh.shape.get(tp_axis, 1))
        if tp > 1 and cfg.num_kv_heads % tp:
            raise ValueError(
                f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads} "
                "for tensor-parallel paged serving")

        cache = paged_engine.init_paged_cache(
            cfg, num_pages=num_pages, page_size=page_size, batch=max_slots,
            max_pages_per_slot=self.max_pages_per_slot)
        self.state = {
            "pools": _split_cache(cache),
            "hist": jnp.zeros((max_slots, max_context), jnp.int32),
        }
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ax = tp_axis if tp > 1 else None

            def put(x, spec):
                return jax.device_put(x, NamedSharding(mesh, spec))

            self.state = {
                "pools": {
                    name: put(pool,
                              P(None, None, ax, None, None)
                              if pool.ndim == 5 else P(None, None, ax, None))
                    for name, pool in self.state["pools"].items()},
                "hist": put(self.state["hist"], P()),
            }
        # host-authoritative scheduling state
        self.tables = np.full((max_slots, self.max_pages_per_slot),
                              num_pages, np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self.active = np.zeros((max_slots,), bool)
        self.last_token = np.zeros((max_slots,), np.int32)
        self.stop_len = np.zeros((max_slots,), np.int32)

        # speculative-efficiency counters: committed tokens per model
        # round (mean accepted length + 1); plain decode reports ~1.0
        self.decode_rounds = 0
        self.decode_tokens_committed = 0
        self.tokens_emitted = 0  # lifetime emitted tokens (bench/metrics)

        self._slots: list[_Slot | None] = [None] * max_slots
        self._jobs: list[_AdmitJob] = []
        self._pending: collections.deque[Request] = collections.deque()
        self._lock = threading.Lock()
        self._step_lock = threading.Lock()
        self._rng = jax.random.key(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- client API ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: int | None = None, stream=None) -> Request:
        if self._stop.is_set():
            raise RuntimeError("server is stopped; not accepting requests")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        _bucket(len(prompt), self.prompt_buckets)  # raises if too long
        max_new = (self.infer_cfg.max_decode_len if max_new_tokens is None
                   else max_new_tokens)
        # leave room for the last speculative window's writes
        max_new = min(max_new, self.max_context - len(prompt) - self.window)
        if max_new <= 0:
            raise ValueError(
                f"prompt of {len(prompt)} tokens leaves no room to decode "
                f"within max_context={self.max_context}")
        req = Request(prompt=list(prompt), max_new_tokens=max_new,
                      stream=stream)
        with self._lock:
            self._pending.append(req)
        return req

    def generate(self, prompts, *, max_new_tokens=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def prefix_cache_stats(self):
        return self.allocator.stats()

    # -- internals ----------------------------------------------------------

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _emit(self, req: Request, token: int, logprob: float) -> bool:
        if token == self.infer_cfg.eos_token_id:
            req.finish_reason = "eos"
            return True
        req.tokens.append(token)
        self.tokens_emitted += 1
        req.logprobs.append(float(logprob))
        if req.stream is not None:
            req.stream(token)
        if len(req.tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
            return True
        return False

    def _finish(self, slot_id: int) -> None:
        slot = self._slots[slot_id]
        committed = slot.prompt + slot.req.tokens
        self.allocator.release(slot.pages, committed)
        self._slots[slot_id] = None
        self.tables[slot_id, :] = self.allocator.num_pages  # sentinel
        self.active[slot_id] = False
        self.lengths[slot_id] = 0
        slot.req._done.set()

    # -- admission ----------------------------------------------------------

    def _rem_bucket(self, rem: int) -> int:
        if rem <= self.prefill_chunk:
            return _bucket(rem, self._rem_buckets)
        return -(-rem // self.prefill_chunk) * self.prefill_chunk

    def _start_admissions(self) -> None:
        """Pop pending requests into slots (pages permitting) and build
        bucketed chunked-prefill jobs."""
        staged: list[int] = []
        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
            while self._pending and free:
                req = self._pending[0]
                shared, shared_len = self.allocator.lookup_prefix(req.prompt)
                total = len(req.prompt) + req.max_new_tokens + self.window
                need = -(-total // self.page_size) - len(shared)
                fresh = self.allocator.alloc(max(0, need))
                if fresh is None:
                    self.allocator.release(shared, req.prompt[:shared_len])
                    if self.num_active == 0 and not self._jobs:
                        # nothing running will ever free pages: the pool
                        # is simply too small for this request
                        self._pending.popleft()
                        req.finish_reason = (
                            "error: request needs more pages than the "
                            "pool can ever provide")
                        req._done.set()
                        continue
                    break
                self._pending.popleft()
                slot_id = free.pop(0)
                slot = _Slot(req=req, prompt=list(req.prompt),
                             pages=shared + fresh, shared_len=shared_len,
                             stop_len=len(req.prompt) + req.max_new_tokens)
                self._slots[slot_id] = slot
                self.tables[slot_id, :] = self.allocator.num_pages
                self.tables[slot_id, :len(slot.pages)] = slot.pages
                self.lengths[slot_id] = shared_len
                self.stop_len[slot_id] = slot.stop_len
                self.active[slot_id] = False  # live once admission is done
                staged.append(slot_id)
        if not staged:
            return
        # group by remainder bucket => uniform chunk schedule per job
        by_bucket: dict[int, list[int]] = {}
        for slot_id in staged:
            slot = self._slots[slot_id]
            rb = self._rem_bucket(len(slot.prompt) - slot.shared_len)
            by_bucket.setdefault(rb, []).append(slot_id)
        pad_tok = self.infer_cfg.pad_token_id
        for rb, slot_ids in by_bucket.items():
            w = min(rb, self.prefill_chunk)
            n_chunks = -(-rb // w)
            g = len(slot_ids)
            pb = _bucket(max(len(self._slots[s].prompt) for s in slot_ids),
                         self.prompt_buckets)
            job = _AdmitJob(
                slots=list(slot_ids), chunk_w=w, n_chunks=n_chunks,
                rows=np.full((g, n_chunks * w), pad_tok, np.int32),
                rem_lens=np.zeros((g,), np.int32),
                base_lens=np.zeros((g,), np.int32),
                prompt_rows=np.full((g, pb), pad_tok, np.int32),
                prompt_lens=np.zeros((g,), np.int32),
                toks=np.zeros((g,), np.int32),
                lps=np.zeros((g,), np.float64),
                got=np.zeros((g,), bool))
            for i, sid in enumerate(slot_ids):
                slot = self._slots[sid]
                rem_toks = slot.prompt[slot.shared_len:]
                job.rows[i, :len(rem_toks)] = rem_toks
                job.rem_lens[i] = len(rem_toks)
                job.base_lens[i] = slot.shared_len
                job.prompt_rows[i, :len(slot.prompt)] = slot.prompt
                job.prompt_lens[i] = len(slot.prompt)
            self._jobs.append(job)

    def _run_one_chunk(self, job: _AdmitJob) -> None:
        c = job.next_chunk
        w = job.chunk_w
        g = len(job.slots)
        gp = _pad_pow2(g)  # bound compiles: group rows pad to a power of 2

        def pad_rows(a, fill):
            if g == gp:
                return a
            padded = np.full((gp,) + a.shape[1:], fill, a.dtype)
            padded[:g] = a
            return padded

        chunk = pad_rows(job.rows[:, c * w:(c + 1) * w],
                         self.infer_cfg.pad_token_id)
        g_lens = pad_rows(job.base_lens + c * w, 0)
        slot_ids = pad_rows(np.asarray(job.slots, np.int32), self.max_slots)
        g_tables = np.full((gp, self.max_pages_per_slot),
                           self.allocator.num_pages, np.int32)
        g_tables[:g] = self.tables[np.asarray(job.slots)]
        sample_at = pad_rows(np.clip(job.rem_lens - 1 - c * w, 0, w - 1), 0)
        in_range = ((job.rem_lens - 1) >= c * w) & (
            (job.rem_lens - 1) < (c + 1) * w)
        prompt_rows = pad_rows(job.prompt_rows, self.infer_cfg.pad_token_id)
        prompt_lens = pad_rows(job.prompt_lens, 0)

        self.state, toks, lps = _prefill_chunk(
            self.params, self.state, jnp.asarray(chunk),
            jnp.asarray(g_lens, jnp.int32), jnp.asarray(g_tables),
            jnp.asarray(sample_at, jnp.int32), jnp.asarray(slot_ids),
            jnp.asarray(prompt_rows), jnp.asarray(prompt_lens, jnp.int32),
            self._next_rng(), cfg=self.cfg, infer_cfg=self.infer_cfg,
            scatter_prompt=(c == 0), mesh=self.mesh)
        toks, lps = jax.device_get((toks, lps))
        toks, lps = np.asarray(toks)[:g], np.asarray(lps)[:g]
        job.toks = np.where(in_range, toks, job.toks)
        job.lps = np.where(in_range, lps, job.lps)
        job.got |= in_range
        job.next_chunk += 1

        if job.next_chunk >= job.n_chunks:
            # admission complete: activate slots, emit first tokens
            for i, sid in enumerate(job.slots):
                slot = self._slots[sid]
                assert bool(job.got[i]), "first-token sample never captured"
                self.lengths[sid] = len(slot.prompt)
                self.last_token[sid] = int(job.toks[i])
                self.active[sid] = True
                if self._emit(slot.req, int(job.toks[i]),
                              float(job.lps[i])):
                    self._finish(sid)
            self._jobs.remove(job)

    # -- decode -------------------------------------------------------------

    def _chunk_rounds(self) -> int:
        """Rounds this dispatch: bounded by decode_chunk and the tightest
        remaining budget (in rounds), rounded down to a power of two."""
        rem = [s.req.max_new_tokens - len(s.req.tokens)
               for i, s in enumerate(self._slots)
               if s is not None and self.active[i]]
        if not rem:
            return 1
        n = max(1, min(self.decode_chunk, -(-min(rem) // self.window)))
        p = 1
        while p * 2 <= n:
            p *= 2
        return p

    def _decode_dispatch(self) -> None:
        n = self._chunk_rounds()
        live = self.active.copy()
        # non-live slots (mid-admission or empty) must not write through
        # their real tables — the batch-wide window would clobber pages
        # their prefill chunks are filling
        masked_tables = np.where(live[:, None], self.tables,
                                 self.allocator.num_pages)
        args = (jnp.asarray(self.lengths), jnp.asarray(masked_tables),
                jnp.asarray(self.last_token), jnp.asarray(live))
        if self.spec_drafts > 0:
            self.state, lens, last, (toks, lps, counts) = _spec_rounds(
                self.params, self.state, *args,
                jnp.asarray(self.stop_len), self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_rounds=n,
                n_drafts=self.spec_drafts, mesh=self.mesh)
            toks, lps, counts, lens, last = jax.device_get(
                (toks, lps, counts, lens, last))
        else:
            self.state, lens, last, (toks, lps, counts) = _decode_rounds(
                self.params, self.state, *args, self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_rounds=n,
                mesh=self.mesh)
            toks, lps, counts, lens, last = jax.device_get(
                (toks, lps, counts, lens, last))
            toks, lps = toks[:, :, None], lps[:, :, None]

        self.lengths = np.asarray(lens).copy()
        self.last_token = np.asarray(last).copy()
        counts = np.asarray(counts)
        n_live = int(live.sum())
        self.decode_rounds += int(counts.shape[0]) * n_live
        self.decode_tokens_committed += int(counts.sum())
        for r in range(toks.shape[0]):
            for sid in range(self.max_slots):
                slot = self._slots[sid]
                if slot is None or not self.active[sid]:
                    continue
                for t in range(int(counts[r, sid])):
                    if self._emit(slot.req, int(toks[r, sid, t]),
                                  float(lps[r, sid, t])):
                        self._finish(sid)
                        break

    # -- scheduler ----------------------------------------------------------

    def step(self) -> int:
        """One scheduler iteration: start admissions, run ONE prefill
        chunk per in-flight admission job (chunked prefill interleaving),
        then one decode dispatch. Thread-safe."""
        with self._step_lock:
            self._start_admissions()
            for job in list(self._jobs):
                self._run_one_chunk(job)
            if self.active.any():
                self._decode_dispatch()
            return self.num_active

    def run_until_idle(self) -> None:
        while self.num_pending or self.num_active or self._jobs:
            self.step()

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            pending, self._pending = list(self._pending), collections.deque()
        for sid, slot in enumerate(self._slots):
            if slot is not None:
                # release with tokens=[] — drops the refs (keeping the
                # allocator consistent for any future recovery path) but
                # keys NOTHING: a failed dispatch may have left these
                # pages half-written, so they must not enter the prefix
                # cache as valid KV
                self.allocator.release(slot.pages, [])
                self.tables[sid, :] = self.allocator.num_pages
                self.active[sid] = False
                self.lengths[sid] = 0
                slot.req.finish_reason = f"error: {exc!r}"
                slot.req._done.set()
                self._slots[sid] = None
        self._jobs.clear()
        for req in pending:
            req.finish_reason = f"error: {exc!r}"
            req._done.set()

    def serve_forever(self, idle_sleep_s: float = 0.002) -> None:
        while not self._stop.is_set():
            try:
                busy = self.step()
            except Exception as exc:  # noqa: BLE001 — must not hang clients
                import traceback
                traceback.print_exc()
                self._fail_all(exc)
                self._stop.set()
                return
            if busy == 0 and self.num_pending == 0 and not self._jobs:
                self._stop.wait(idle_sleep_s)

    def start(self) -> "PagedInferenceServer":
        self._stop.clear()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="paged-inference-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

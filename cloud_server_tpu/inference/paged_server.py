"""Paged continuous-batching server: block-table KV, shared prefixes,
chunked prefill, and in-server speculative decoding.

This is the successor of `inference.server.InferenceServer` (which keeps
the contiguous slot cache). What the paged design buys:

  * Memory scales with resident tokens, not max_slots x max_len: the pool
    is `num_pages` fixed-size pages; a slot holds ceil(context / ps)
    pages. More concurrent requests fit in the same HBM whenever requests
    are shorter than max_context or share prefixes.
  * Prefix reuse is GENERAL (radix-style, page granularity): any request
    whose token prefix matches cached pages — same system prompt, same
    few-shot header, a multi-turn follow-up replaying the conversation
    (generated tokens included) — skips prefill for the shared pages.
    No server-lifetime single prefix; the cache is learned from traffic
    and LRU-evicted under memory pressure (inference/block_allocator.py).
  * Chunked prefill: admissions run as a sequence of bounded window
    dispatches (`prefill_chunk` tokens each), so one long prompt never
    stalls active decodes for its whole prefill — inter-token latency
    stays bounded (the serving bench measures it).
  * STALL-FREE MIXED BATCHING (scheduler="mixed", the default): while
    any admission is in flight, each scheduler iteration fuses ONE
    ragged prefill group (every admitting slot the token budget
    selected, each at its own width — no remainder-bucket grouping) and
    the full multi-round decode dispatch into a single jitted program
    with a single host sync. The alternating scheduler (kept as
    scheduler="alternating") instead pays one dispatch + sync per
    admission group plus one per decode dispatch, and shrinks decode to
    `admit_decode_chunk` rounds whenever admissions are running — which
    is exactly the churn cliff the r5 bench measured (decode collapsing
    to ~10 steps across a whole admission phase). Greedy and seeded
    outputs are token-for-token identical under both schedulers
    (tests/test_mixed_scheduler.py). `mixed_token_budget` caps the
    tokens packed per iteration (decode rows first, prefill fills the
    rest, one minimal chunk guaranteed so TTFT stays bounded); the
    default is work-conserving.
  * Decode batch COMPACTION (both schedulers): decode dispatches carry
    one row per LIVE slot (pow2-padded) with a slot_ids indirection
    into the per-slot device state, so attention gathers and matmuls
    scale with occupancy instead of max_slots — a half-admitted batch
    no longer pays full-batch decode cost. Fully-live batches skip the
    indirection entirely (the pre-compaction program).
  * Speculative decoding IS the decode loop (spec_drafts > 0): per-slot
    n-gram proposals drafted on device from each slot's token history,
    verified batch-wide in one W = drafts+1 window, committed per slot
    with the exact accept/residual rule (`speculative._accept_point_mass`
    — output distribution provably unchanged; token-for-token greedy).
    No draft model, no extra memory; repetition-heavy decodes commit
    several tokens per model pass. With a DRAFT MODEL
    (`draft_params`/`draft_cfg`) the classic draft/verify loop runs the
    same way — and BOTH sources now compose with the mixed scheduler:
    the draft model's chunk prefill and per-round decode discipline are
    part of the one fused `_mixed_step` program, so speculation no
    longer forces the alternating scheduler.
  * ADAPTIVE speculation (on by default whenever spec_drafts > 0;
    `spec_control=` / `--spec-control`, inference/spec_control.py): a
    host-side controller tracks a rolling accept rate per slot from
    the per-round counts the scheduler already syncs and tunes each
    slot's draft length between 0 (plain decode) and spec_drafts with
    hysteresis; each row commits at most its own length (exact
    truncation; dispatch width quantized to {0, spec_drafts} — one
    compiled program per static width). Low-acceptance
    workloads converge to plain decode instead of paying dead verify
    windows; QoS generated-token buckets are charged only for
    committed tokens while rejected draft work lands on a per-tenant
    wasted-speculation counter.

  * ASYNC DOUBLE-BUFFERED SCHEDULING (`InferConfig.overlap` /
    `overlap=`, default on; mixed scheduler only): JAX dispatch is
    async, so the scheduler pipelines the loop instead of serializing
    host policy against the device. Each step plans iteration N+1 —
    sweep, QoS/DRR admission, deadline checks, chain growth, and the
    whole numpy dispatch build — against the last COMMITTED ledger
    plus the in-flight dispatch's deterministic effects (job cursors
    advance by the takes it was launched with; planned lengths use
    the worst-case rounds*window bound) WHILE the device executes
    iteration N; then it pays the one sanctioned `device_get` commit,
    patches the handful of data-dependent inputs (row lengths / last
    tokens / the live mask, re-read from the just-committed ledger),
    and launches N+1. Only the commit + patch + launch tail stays on
    the serialized critical path — `host_gap_frac` in the flight
    records measures exactly that residual. Write-safety: while a
    dispatch is in flight the planner NEVER releases pages (no
    preemption, no slot teardown — sweep reaps are deferred to just
    after the commit), statically enforced by the dispatch-discipline
    pass's DD5 rule; on page famine the plan degrades its round count
    and the pipeline drains so the next sequential iteration can run
    the full preemption escalation. Greedy and seeded outputs are
    token-for-token identical with overlap on or off (scheduling is
    output-invariant by the same property the mixed/alternating
    parity pins); overlap=False falls back to the byte-identical
    sequential loop.

Scheduling state is HOST-authoritative (tables, lengths, active,
last_token live in numpy and ride into each dispatch as small inputs);
the device owns only the big buffers (page pools + per-slot token
history), donated through every dispatch. One device_get per scheduler
iteration, amortised over `decode_chunk` (speculative) rounds
(multi-token scheduling, as in the contiguous server).

Write-safety rules the scheduler maintains (see paged_engine for why
writes through sentinel tables drop):
  * decode dispatches get SENTINEL table rows for every non-live slot, so
    a slot mid-admission can never have its freshly prefilled pages
    clobbered by the concurrent batch-wide decode window;
  * a slot's chain always covers its next dispatch's window writes —
    either reserved whole at admission (allocation="reserve": prompt +
    max_new + window slack, no mid-flight OOM possible) or grown
    just-in-time per dispatch (allocation="ondemand", the default:
    admission takes prompt + one window; `_extend_chains` allocates
    ahead of each decode dispatch and, on pool exhaustion, preempts the
    youngest slot — its pages release into the radix cache and its
    request requeues as a continuation whose re-prefill is mostly cache
    hits). On-demand never parks worst-case max_new headroom, so
    sustained concurrency at equal HBM is strictly higher
    (tests/test_paged_server.py::test_ondemand_concurrency_beyond_reservation).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import threading
import time
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference import paged_engine, sampling
from cloud_server_tpu.inference.block_allocator import BlockAllocator
from cloud_server_tpu.inference.grammar import DEAD as _GDEAD
from cloud_server_tpu.inference.iteration_profile import (
    OVERLAP_PHASES, derive_gap_fields)
from cloud_server_tpu.inference.sampling import (
    SamplingParams, SamplingRows, make_rows, sample_from_probs,
    sample_logits, sample_logits_rows, sampling_probs,
    sampling_probs_rows)
from cloud_server_tpu.inference.server import (
    QueueFullError, Request, _StepTracer, _bucket, _token_logprobs,
    emit_token, resolve_seed)
from cloud_server_tpu.inference.spec_control import resolve_controller
from cloud_server_tpu.inference.speculative import (
    _TAG_DRAFT, _accept_drafts, _accept_point_mass, _ngram_drafts,
    _row_pos_keys, sample_from_probs_keyed)
from cloud_server_tpu.utils.serving_metrics import (
    FlightRecorder, ServingMetrics)


def _pow2_buckets(lo: int, hi: int) -> list[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    return out + [hi]


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# Neutral per-field fills for PADDING rows of a gathered SamplingRows
# (temp 0 = greedy, rep/top_p 1, bias slots out-of-vocab): padding
# samples are discarded, but rep=0 would divide to inf/NaN and trip
# jax_debug_nans even on discarded rows. Fields absent here fill with 0.
_SAMP_PAD_FILLS = {"top_p": 1.0, "rep": 1.0,
                   "bias_ids": sampling._BIAS_PAD}


def _gather_samp_rows(samp_rows, idx, n_real):
    """Per-slot SamplingRows rows gathered at `idx` (pre-clipped), with
    rows past n_real overwritten by the neutral pad fills."""
    out = []
    for name, dst in zip(SamplingRows._fields, samp_rows):
        rows = dst[idx].copy()
        rows[n_real:] = _SAMP_PAD_FILLS.get(name, 0)
        out.append(rows)
    return SamplingRows(*out)


def _gather_slot_state(state, slot_ids, batch_idx):
    """Compaction prologue shared by the decode cores: row views of the
    per-slot device state (see _decode_plain_core's COMPACTION note).
    slot_ids=None means rows ARE slots (no gathers)."""
    full_gstate = state["gstate"]
    n_slots = full_gstate.shape[0]
    sids = batch_idx if slot_ids is None else slot_ids
    sids_r = (batch_idx if slot_ids is None
              else jnp.clip(slot_ids, 0, n_slots - 1))
    pm = state.get("prompt_mask")  # None until penalties materialize
    if pm is not None and slot_ids is not None:
        pm = pm[sids_r]
    full_oc = state.get("out_counts")
    oc0 = (full_oc if slot_ids is None or full_oc is None
           else full_oc[sids_r])
    gstate0 = full_gstate if slot_ids is None else full_gstate[sids_r]
    return sids, sids_r, pm, oc0, gstate0, full_oc, full_gstate


def _scatter_slot_state(new_state, slot_ids, sids, oc, gstate,
                        full_oc, full_gstate):
    """Compaction epilogue: gathered gstate/out_counts rows back into the
    full per-slot state (sentinel rows drop)."""
    if slot_ids is None:
        new_state["gstate"] = gstate
        if oc is not None:
            new_state["out_counts"] = oc
        return
    new_state["gstate"] = full_gstate.at[sids].set(gstate, mode="drop")
    if oc is not None:
        new_state["out_counts"] = full_oc.at[sids].set(oc, mode="drop")


# ---------------------------------------------------------------------------
# Jitted dispatches (module-level so compiles are shared across servers)
# ---------------------------------------------------------------------------


def _grammar_mask(grammar, gid, st, eos_id):
    """Next-state row(s) + allowed-token mask from DFA state(s).

    gid: (B,) or (B, 1); st: (B,) or (B, W). DEAD states allow nothing
    (their garbage samples are never committed). EOS is allowed exactly
    at accepting states. gid 0 (the identity grammar) is unconditionally
    live at state 0 — a stale device state left by a slot's previous
    constrained occupant must never mask an unconstrained request. THE
    single mask construction — prefill, decode, and both speculative
    walks all call this."""
    tb, ac = grammar
    ident = gid == 0
    idx = jnp.where(ident, 0, jnp.maximum(st, 0))
    nrow = tb[gid, idx]
    live_st = (st != _GDEAD) | ident
    amask = (nrow != _GDEAD) & live_st[..., None]
    if eos_id >= 0:
        amask = amask.at[..., eos_id].set(ac[gid, idx] & live_st)
    return nrow, amask


def _make_cache(pools, lengths, tables):
    return paged_engine.PagedKVCache(
        k=pools["k"], v=pools["v"], lengths=lengths, tables=tables,
        k_scale=pools.get("k_scale"), v_scale=pools.get("v_scale"))


def _split_cache(cache):
    pools = {"k": cache.k, "v": cache.v}
    if cache.k_scale is not None:
        pools["k_scale"] = cache.k_scale
        pools["v_scale"] = cache.v_scale
    return pools


def _prefill_core(params, state, chunk, g_lens, g_tables, sample_at,
                  slot_ids, prompt_rows, prompt_lens, rng,
                  samp_rows, orig_lens, count_mask,
                  gid=None, gstate0=None, grammar=None,
                  lora=None, aid=None,
                  draft_params=None, widths=None, scatter_mask=None, *,
                  cfg: ModelConfig, infer_cfg: InferConfig,
                  scatter_prompt: bool, mesh=None, draft_cfg=None,
                  use_rows: bool = False, use_bias: bool = False):
    """One admission window for a (padded) G-row group — the traced body
    shared by `_prefill_chunk` (alternating scheduler: uniform chunk
    width per group) and `_mixed_step` (mixed scheduler: RAGGED per-row
    `widths`, since the token budget hands every admitting row a
    different width in the same call, and a per-row `scatter_mask`,
    since rows at different admission progress share one dispatch).

    chunk: (G, Wc) tokens for positions [g_lens, g_lens + Wc) per row —
    rows at different offsets, which is how shared prefixes resume deeper
    and how successive chunks continue. sample_at: in-window index of
    each row's LAST true prompt token (clamped; the caller keeps the
    sample only when it truly falls inside this chunk). On the first
    chunk (`scatter_prompt`, further restricted to `scatter_mask` rows
    when given) each row's full prompt is written into its slot's device
    history for n-gram drafting. Padding rows carry slot_id == max_slots
    and sentinel tables: every scatter drops.

    Per-request sampling state: `orig_lens` (G,) marks the original
    prompt / generated boundary inside `prompt_rows` (continuations from
    a preemption carry already-generated tokens, which must count as
    OUTPUT for presence/frequency penalties); `count_mask` (G,) flags
    the chunk where each row's first-token sample is truly captured.
    `samp_rows` always lands in the slots' row state; `use_rows`
    (static) additionally samples the first token through it.

    Returns (state', first-token candidates (G,), their logprobs (G,)).
    """
    cache = _make_cache(state["pools"], g_lens, g_tables)
    logits, cache = paged_engine.window_forward(
        params, chunk, cfg, cache, logits_at=sample_at, mesh=mesh,
        lora=lora, aid=aid, widths=widths)
    new_state = dict(state)
    new_state["pools"] = _split_cache(cache)

    has_pen = "prompt_mask" in state  # buffers materialize lazily
    pm = oc = None
    if has_pen:
        pm, oc = state["prompt_mask"], state["out_counts"]
        g, pb = prompt_rows.shape
        vsz = pm.shape[-1]
        rowi = jnp.arange(g)
        if scatter_prompt:
            # rebuild the slots' penalty state from the admission
            # prompt: positions < orig_len are PROMPT presence,
            # [orig_len, prompt_len) are generated-before-preemption
            # OUTPUT counts
            pos = jnp.broadcast_to(jnp.arange(pb)[None, :], (g, pb))
            pm_cols = jnp.where(pos < orig_lens[:, None], prompt_rows,
                                vsz)
            pm_rows = jnp.zeros((g, vsz), bool).at[
                rowi[:, None], pm_cols].set(True, mode="drop")
            oc_cols = jnp.where((pos >= orig_lens[:, None])
                                & (pos < prompt_lens[:, None]),
                                prompt_rows, vsz)
            oc_rows = jnp.zeros((g, vsz), jnp.int32).at[
                rowi[:, None], oc_cols].add(1, mode="drop")
            sc_ids = (slot_ids if scatter_mask is None
                      else jnp.where(scatter_mask, slot_ids, pm.shape[0]))
            pm = pm.at[sc_ids].set(pm_rows, mode="drop")
            oc = oc.at[sc_ids].set(oc_rows, mode="drop")
    amask = None
    if grammar is not None:
        # constrained rows: allowed first tokens from each row's resume
        # state; EOS allowed only at accepting states
        nrow, amask = _grammar_mask(grammar, gid, gstate0,
                                    infer_cfg.eos_token_id)
    if use_rows:
        toks = sample_logits_rows(
            logits, samp_rows, prompt_lens,
            prompt_mask=pm[slot_ids] if has_pen else None,
            out_counts=oc[slot_ids] if has_pen else None,
            eos_id=infer_cfg.eos_token_id, use_bias=use_bias,
            allowed_mask=amask)
    else:
        toks = sample_logits(logits, rng, infer_cfg)
    lps = _token_logprobs(logits, toks)
    if gstate0 is not None:
        # advance ONLY the rows captured THIS chunk — a multi-chunk job
        # revisits rows whose sample landed in an earlier chunk, and
        # rewriting those would reset their already-advanced state.
        # Grammar-free groups still SCATTER (their gstate0, i.e. 0):
        # admission must overwrite whatever DFA state the slot's
        # previous occupant left behind — DEAD is sticky, and a stale
        # DEAD row would mask every token for the new request the
        # moment any other live slot is constrained.
        if grammar is not None:
            g_rows = prompt_rows.shape[0]
            nstate = nrow[jnp.arange(g_rows), toks]
        else:
            nstate = gstate0
        gs = state["gstate"]
        cap_idx = jnp.where(count_mask, slot_ids, gs.shape[0])
        new_state["gstate"] = gs.at[cap_idx].set(nstate, mode="drop")
    if has_pen:
        # the captured first token is this slot's first generated token
        oc = oc.at[slot_ids, toks].add(count_mask.astype(jnp.int32),
                                       mode="drop")
        new_state["prompt_mask"] = pm
        new_state["out_counts"] = oc
    if draft_cfg is not None:
        # the draft model prefills the same chunk into ITS pools (same
        # page ids / tables, draft geometry) so in-server draft-model
        # speculation has the full context cached — including shared
        # prefix pages, which carry the draft kv alongside the target's.
        # The mixed scheduler's RAGGED groups pass per-row `widths`:
        # the draft's writes and attention honor each row's true
        # progress exactly like the target's call above
        dcache = _make_cache(state["draft_pools"], g_lens, g_tables)
        _, dcache = paged_engine.window_forward(
            draft_params, chunk, draft_cfg, dcache, logits_at=None,
            mesh=mesh, widths=widths)
        new_state["draft_pools"] = _split_cache(dcache)
    hist = state["hist"]
    if scatter_prompt:
        pb = prompt_rows.shape[1]
        cols = jnp.broadcast_to(jnp.arange(pb)[None, :], prompt_rows.shape)
        keep = cols < prompt_lens[:, None]
        if scatter_mask is not None:
            keep &= scatter_mask[:, None]
        cols = jnp.where(keep, cols, hist.shape[1])
        hist = hist.at[slot_ids[:, None], cols].set(prompt_rows,
                                                    mode="drop")
    new_state["hist"] = hist
    return new_state, toks, lps


# Alternating-scheduler admission dispatch: `_prefill_core` at one
# uniform chunk width per group (widths/scatter_mask default to None —
# every row full-width, every row scattering on its first chunk).
_prefill_chunk = partial(jax.jit,
                         static_argnames=("cfg", "infer_cfg",
                                          "scatter_prompt", "mesh",
                                          "draft_cfg", "use_rows",
                                          "use_bias"),
                         donate_argnums=(1,))(_prefill_core)


def _decode_plain_core(params, state, lengths, tables, last_token, live,
                       rng, samp_rows, gid=None, grammar=None,
                       lora=None, aid=None, slot_ids=None, *,
                       cfg: ModelConfig,
                       infer_cfg: InferConfig, n_rounds: int, mesh=None,
                       use_rows: bool = False, use_bias: bool = False):
    """n_rounds plain decode steps (W=1) in one dispatch (lax.scan).
    Traced body shared by `_decode_rounds` and `_mixed_step`.

    `live` slots advance one token per round; the rest are frozen (their
    writes drop through the sentinel tables the caller passes).
    `use_rows` (static) samples through the per-request SamplingRows,
    advancing the generated-token counts for penalties.

    COMPACTION (`slot_ids`): rows may be a gathered subset of slots —
    row i is slot slot_ids[i] (padding rows carry the max_slots
    sentinel, so their per-slot state scatters drop). The per-slot
    device state (hist / gstate / penalty counts) stays full-size;
    lengths / tables / last / samp_rows arrive already gathered. A
    half-empty batch then dispatches at half the rows — attention
    gathers and matmuls scale with LIVE slots, not max_slots, which is
    what keeps decode affordable while admissions hold slots.
    slot_ids=None means rows ARE slots (the uncompacted layout).

    Returns (state', lengths', last', (toks (R, Bg), lps (R, Bg),
    counts (R, Bg) int32)) — rows in the caller's gathered order.
    """
    pad = infer_cfg.pad_token_id
    batch_idx = jnp.arange(lengths.shape[0])
    (sids, sids_r, pm, oc0, gstate0,
     full_oc, full_gstate) = _gather_slot_state(state, slot_ids, batch_idx)

    def body(carry, rng_t):
        lengths, last, hist, pools, oc, gstate = carry
        # `last` is the committed token at sequence position `lengths`
        # (this round writes its kv there); record it in the history so
        # drafting/multi-turn reads see an unbroken token sequence
        cols = jnp.where(live, lengths, hist.shape[1])
        hist = hist.at[sids, cols].set(last, mode="drop")
        cache = _make_cache(pools, lengths, tables)
        logits, cache = paged_engine.window_forward(
            params, last[:, None], cfg, cache,
            logits_at=jnp.zeros_like(lengths), mesh=mesh,
            lora=lora, aid=aid)
        amask = None
        if grammar is not None:
            nrow, amask = _grammar_mask(grammar, gid, gstate,
                                        infer_cfg.eos_token_id)
        if use_rows:
            # the sampled token sits at position lengths + 1 (`last`
            # occupies `lengths`); the admission chunk folds the prompt
            # length, so positions never collide within a request
            tok = sample_logits_rows(logits, samp_rows, lengths + 1,
                                     prompt_mask=pm, out_counts=oc,
                                     eos_id=infer_cfg.eos_token_id,
                                     use_bias=use_bias,
                                     allowed_mask=amask)
            if oc is not None:
                oc = oc.at[batch_idx, tok].add(live.astype(jnp.int32))
        else:
            tok = sample_logits(logits, rng_t, infer_cfg)
        if grammar is not None:
            # sticky DEAD: a dead row (post-EOS scan tail) must never
            # resurrect through the max(st, 0) clamp
            gstate = jnp.where(live & (gstate != _GDEAD),
                               nrow[batch_idx, tok], gstate)
        lp = _token_logprobs(logits, tok)
        tok = jnp.where(live, tok, pad)
        new_len = jnp.where(live, lengths + 1, lengths)
        last = jnp.where(live, tok, last)
        return ((new_len, last, hist, _split_cache(cache), oc, gstate),
                (tok, lp, live.astype(jnp.int32)))

    (lengths, last, hist, pools, oc, gstate), out = lax.scan(
        body, (lengths, last_token, state["hist"], state["pools"],
               oc0, gstate0),
        jax.random.split(rng, n_rounds))
    new_state = dict(state)
    new_state["pools"] = pools
    new_state["hist"] = hist
    _scatter_slot_state(new_state, slot_ids, sids, oc, gstate,
                        full_oc, full_gstate)
    return new_state, lengths, last, out


_decode_rounds = partial(jax.jit,
                         static_argnames=("cfg", "infer_cfg", "n_rounds",
                                          "mesh", "use_rows", "use_bias"),
                         donate_argnums=(1,))(_decode_plain_core)


def _spec_core(params, state, lengths, tables, last_token, live,
               stop_len, rng, samp_rows, gid=None, grammar=None,
               lora=None, aid=None,
               draft_params=None, slot_ids=None, draft_limit=None, *,
               cfg: ModelConfig, infer_cfg: InferConfig, n_rounds: int,
               n_drafts: int, mesh=None, draft_cfg=None,
               use_rows: bool = False, use_bias: bool = False):
    """n_rounds speculative rounds in one dispatch. Traced body shared
    by `_spec_rounds` and `_mixed_step`.

    Each round drafts `n_drafts` tokens per slot — from a DRAFT MODEL
    decoding against its own paged cache (draft_params/draft_cfg;
    classic speculative decoding) or from the slot's device-resident
    history (prompt-lookup n-grams) — scores the (drafts+1)-token window
    in ONE batched window_forward, and commits each slot's accepted
    prefix plus the corrective/bonus token (exact accept rule — see
    speculative._accept_drafts / _accept_point_mass). Commits are
    capped at stop_len so a slot never outruns its page chain.

    Draft-model cache discipline (mirrors speculative_generate): G+1
    draft decode steps per round — step j writes the draft kv of its
    input token at position lengths + j, so accepted positions are
    already cached and the corrective token's kv lands when the next
    round's step 0 feeds it. Stale draft entries past the commit point
    are masked by lengths and overwritten by later rounds, exactly like
    the target pool.

    Per-request sampling (`use_rows`): penalties stay EXACT through the
    window — target probabilities at window position i use the counts as
    of that position (base counts + the drafts committed before i, a
    shifted cumulative one-hot), and the draft model's q at step j uses
    the same construction, so the accept rule compares the identical
    distributions plain per-token decoding would have sampled from.

    COMPACTION (`slot_ids`): as in `_decode_plain_core` — rows may be a
    gathered subset of slots; per-slot device state stays full-size and
    scatters go through slot_ids (sentinel rows drop).

    ADAPTIVE draft lengths (`draft_limit`, (Bg,) int32): each row
    commits at most draft_limit + 1 tokens per round — the exact same
    truncation the stop_len cap performs, so a row at limit 0 is plain
    decode riding the speculative window (its one committed token is
    the draft if accepted else the corrective: the marginal is the
    target distribution either way, and at temperature 0 it is THE
    greedy token). The dispatch still drafts/verifies n_drafts
    positions for every row; the host drops n_drafts to 0 (the plain
    program) once every live slot is off (spec_control.py).

    Seeded requests (`use_rows`): the draft-model proposal, accept
    uniform, and corrective draws are POSITION-KEYED on tagged streams
    of the request's seed (speculative._row_pos_keys), so at a fixed
    draft length a seeded speculative stream is identical under both
    schedulers, and commit truncation (stop_len / draft_limit) replays
    transparently. Mid-stream LENGTH changes keep distributional
    exactness but not draw-for-draw replay at temperature > 0 (see
    speculative.py's stream-tag note); greedy is exact throughout.

    Returns (state', lengths', last',
    (toks (R, Bg, G+1), lps (R, Bg, G+1), counts (R, Bg))).
    """
    g = n_drafts
    b = lengths.shape[0]
    pad = infer_cfg.pad_token_id
    batch_idx = jnp.arange(b)
    j = jnp.arange(g + 1)[None, :]
    use_draft = draft_cfg is not None
    (sids, sids_r, pm, oc0, gstate_init,
     full_oc, full_gstate) = _gather_slot_state(state, slot_ids, batch_idx)

    def body(carry, rng_t):
        lengths, last, hist, pools, dpools, oc, gstate = carry
        rng_acc, rng_draft = jax.random.split(rng_t)
        can_commit = live & (lengths < stop_len)

        # `last` is the committed token at sequence position `lengths`;
        # write it into the history BEFORE drafting so bigram lookups
        # spanning the prompt/generated boundary see the true sequence
        cols_last = jnp.where(live, lengths, hist.shape[1])
        hist = hist.at[sids, cols_last].set(last, mode="drop")
        hist_rows = hist if slot_ids is None else hist[sids_r]
        valid = lengths + 1  # committed tokens = [0, lengths] incl. last
        if use_draft:
            def d_step(dc, inp):
                tok, off, rng_d, cnt, st_d = inp
                dcache = _make_cache(dc, lengths + off, tables)
                dlogits, dcache = paged_engine.window_forward(
                    draft_params, tok[:, None], draft_cfg, dcache,
                    logits_at=jnp.zeros_like(lengths), mesh=mesh)
                dmask = None
                if grammar is not None:
                    _, dmask = _grammar_mask(grammar, gid, st_d,
                                             infer_cfg.eos_token_id)
                if use_rows:
                    qp = sampling_probs_rows(
                        dlogits, samp_rows, prompt_mask=pm,
                        out_counts=cnt, positions=lengths + 1 + off,
                        eos_id=infer_cfg.eos_token_id, use_bias=use_bias,
                        allowed_mask=dmask)
                else:
                    qp = sampling_probs(dlogits, infer_cfg)
                if use_rows:
                    # position-keyed proposal stream: schedule- and
                    # draft-length-invariant for seeded requests
                    dkeys = _row_pos_keys(samp_rows.seed,
                                          lengths + 1 + off, _TAG_DRAFT)
                    nxt = sample_from_probs_keyed(qp, dkeys)
                else:
                    nxt = sample_from_probs(qp, rng_d)
                return _split_cache(dcache), (nxt, qp)

            # inputs step j: the token at position lengths + j; step 0
            # feeds `last`, later steps feed the previous step's sample
            # — expressed as a scan whose carried token rides in the
            # iteration outputs, so unroll manually (G is tiny/static)
            toks_j, qps = [], []
            tok = last
            run_cnt = oc  # counts as of each draft position (exactness)
            st_d = gstate
            for step in range(g + 1):
                rng_draft, rd = jax.random.split(rng_draft)
                dpools, (nxt, qp) = d_step(
                    dpools, (tok, jnp.int32(step), rd, run_cnt, st_d))
                if use_rows and run_cnt is not None and step < g:
                    run_cnt = run_cnt.at[batch_idx, nxt].add(1)
                if grammar is not None and step < g:
                    tb, _ = grammar
                    st_d = jnp.where(
                        st_d == _GDEAD, st_d,
                        tb[gid, jnp.maximum(st_d, 0), nxt])
                tok = nxt
                toks_j.append(tok)
                qps.append(qp)
            drafts = jnp.stack(toks_j[:g], axis=1)        # (B, G)
            q_probs = jnp.stack(qps[:g], axis=1)          # (B, G, V)
        else:
            t_prev2 = hist_rows[batch_idx, jnp.maximum(valid - 2, 0)]
            drafts = _ngram_drafts(hist_rows, valid, t_prev2, last, g, pad)
        window = jnp.concatenate([last[:, None], drafts], axis=1)

        cache = _make_cache(pools, lengths, tables)
        vlogits, cache = paged_engine.window_forward(
            params, window, cfg, cache, logits_at=None, all_logits=True,
            mesh=mesh, lora=lora, aid=aid)
        amask_w = None
        if grammar is not None:
            # walk the DFA through the drafts: position i's mask comes
            # from the state AFTER drafts[:i] (exactly the state plain
            # per-token decoding would be in)
            tb, _ = grammar
            sts = [gstate]
            for jj in range(g):
                cur = sts[-1]
                nxt_st = tb[gid, jnp.maximum(cur, 0), drafts[:, jj]]
                sts.append(jnp.where(cur == _GDEAD, cur, nxt_st))
            sts_m = jnp.stack(sts, axis=1)  # (B, G+1)
            _, amask_w = _grammar_mask(grammar, gid[:, None], sts_m,
                                       infer_cfg.eos_token_id)
        if use_rows and pm is not None:
            # counts at window position i = base + drafts committed
            # before i (position 0 scores the token after `last`, which
            # is already in the base counts)
            cum = jnp.cumsum(
                jax.nn.one_hot(drafts, vlogits.shape[-1],
                               dtype=jnp.int32), axis=1)
            counts_w = oc[:, None, :] + jnp.concatenate(
                [jnp.zeros_like(cum[:, :1]), cum], axis=1)
            p_probs = sampling_probs_rows(
                vlogits, samp_rows, prompt_mask=pm, out_counts=counts_w,
                positions=(lengths + 1)[:, None] + j,
                eos_id=infer_cfg.eos_token_id, use_bias=use_bias,
                allowed_mask=amask_w)
        elif use_rows:
            p_probs = sampling_probs_rows(
                vlogits, samp_rows,
                positions=(lengths + 1)[:, None] + j,
                eos_id=infer_cfg.eos_token_id, use_bias=use_bias,
                allowed_mask=amask_w)
        else:
            p_probs = sampling_probs(vlogits, infer_cfg)  # (B, G+1, V)
        seeds = samp_rows.seed if use_rows else None
        pos0 = (lengths + 1) if use_rows else None
        if use_draft:
            n_acc, x = _accept_drafts(drafts, q_probs, p_probs, rng_acc,
                                      seeds=seeds, pos0=pos0)
        else:
            n_acc, x = _accept_point_mass(drafts, p_probs, rng_acc,
                                          seeds=seeds, pos0=pos0)

        drafts_x = jnp.concatenate([drafts, x[:, None]], axis=1)
        committed = jnp.where(j < n_acc[:, None], drafts_x,
                              jnp.where(j == n_acc[:, None],
                                        x[:, None], pad))
        count = jnp.where(can_commit, n_acc + 1, 0)
        if draft_limit is not None:
            # adaptive per-slot draft length (see docstring): the same
            # exact truncation as the stop_len cap below
            count = jnp.minimum(count, draft_limit + 1)
        count = jnp.minimum(count, jnp.maximum(stop_len - lengths, 0))
        toks = jnp.where(j < count[:, None], committed, pad)
        # log P(tok) under the raw target distribution at each window
        # position (position i's logits score the token committed there)
        lps = jnp.take_along_axis(
            jax.nn.log_softmax(vlogits, axis=-1),
            jnp.maximum(toks, 0)[..., None], axis=-1)[..., 0]

        new_len = lengths + count
        # committed[j] is the token at sequence position lengths + 1 + j
        # (position `lengths` holds `last`, written above)
        cols = (lengths + 1)[:, None] + j
        cols = jnp.where(j < count[:, None], cols, hist.shape[1])
        hist = hist.at[sids[:, None], cols].set(toks, mode="drop")
        if use_rows and oc is not None:
            vsz = oc.shape[-1]
            cnt_cols = jnp.where(j < count[:, None], toks, vsz)
            oc = oc.at[batch_idx[:, None], cnt_cols].add(1, mode="drop")
        if grammar is not None:
            tb, _ = grammar
            st = gstate
            for jj in range(g + 1):
                step_st = tb[gid, jnp.maximum(st, 0), toks[:, jj]]
                st = jnp.where((jj < count) & (st != _GDEAD), step_st, st)
            gstate = st
        last_idx = jnp.maximum(count - 1, 0)
        last2 = jnp.where(count > 0, committed[batch_idx, last_idx], last)
        return ((new_len, last2, hist, _split_cache(cache), dpools, oc,
                 gstate),
                (toks, lps, count))

    (lengths, last, hist, pools, dpools, oc, gstate), out = lax.scan(
        body, (lengths, last_token, state["hist"], state["pools"],
               state.get("draft_pools"), oc0, gstate_init),
        jax.random.split(rng, n_rounds))
    new_state = dict(state)
    new_state["pools"] = pools
    new_state["hist"] = hist
    _scatter_slot_state(new_state, slot_ids, sids, oc, gstate,
                        full_oc, full_gstate)
    if dpools is not None:
        new_state["draft_pools"] = dpools
    return new_state, lengths, last, out


_spec_rounds = partial(jax.jit,
                       static_argnames=("cfg", "infer_cfg", "n_rounds",
                                        "n_drafts", "mesh", "draft_cfg",
                                        "use_rows", "use_bias"),
                       donate_argnums=(1,))(_spec_core)


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "n_rounds", "n_drafts",
                          "scatter_prompt", "mesh", "draft_cfg",
                          "use_rows_p", "use_bias_p",
                          "use_rows_d", "use_bias_d"),
         donate_argnums=(1,))
def _mixed_step(params, state,
                chunk, widths, g_lens, g_tables, sample_at, slot_ids,
                prompt_rows, prompt_lens, samp_rows_g, orig_lens,
                count_mask, scatter_mask, gid_g, gstate0_g,
                lengths, tables, last_token, live, stop_len,
                samp_rows_b, gid_b, slot_ids_d, draft_limit,
                rng, grammar=None, lora=None, aid_g=None, aid_b=None,
                draft_params=None, *,
                cfg: ModelConfig, infer_cfg: InferConfig, n_rounds: int,
                n_drafts: int, scatter_prompt: bool, mesh=None,
                draft_cfg=None,
                use_rows_p: bool = False, use_bias_p: bool = False,
                use_rows_d: bool = False, use_bias_d: bool = False):
    """ONE token-budget mixed iteration, ONE jitted program, ONE host
    sync: the ragged prefill group (every admitting row the budget
    selected, each at its own width — `_prefill_core` with per-row
    `widths`/`scatter_mask`) followed by the full multi-round decode
    dispatch (`_decode_plain_core` / `_spec_core`, n_rounds of W = 1 or
    drafts + 1).

    DRAFT-MODEL speculation is fused too (`draft_params`/`draft_cfg`):
    the draft model's chunk prefill rides inside `_prefill_core`
    (ragged widths included) and its per-round G+1 decode discipline
    rides inside `_spec_core`, so the fastest decode path keeps
    stall-free batching instead of forcing the alternating scheduler.
    Draft rounds are funded as decode rows under the token budget — a
    live slot's decode claim is window = n_drafts + 1 tokens per round,
    charged against prefill funding by the host's budget split.

    This is what "fused" means here and why it is stall-free WITHOUT
    extra compute: the alternating scheduler pays one host round trip
    per admission group PLUS one per decode dispatch each iteration, and
    shrinks decode to `admit_decode_chunk` (default 1) rounds while any
    admission is in flight; the mixed program keeps decode at its full
    round count and retires every prefill chunk in the same dispatch, so
    decode throughput under churn stays at its steady-state slope. Both
    halves are exactly the alternating dispatches' traced bodies —
    greedy/seeded outputs are token-for-token identical by construction
    (tests/test_mixed_scheduler.py).

    Prefill rows and decode rows are DISJOINT slots (a slot is live xor
    mid-admission), so program order between the halves is irrelevant;
    slots in neither half ride along fully inert (width 0 and sentinel
    tables in the prefill group, live=False and sentinel tables in the
    decode half) — the sentinel-safety invariant for mid-admission rows.

    Returns (state', first-token candidates (G,), their logprobs (G,),
    lengths', last', (toks (R, B, S), lps (R, B, S), counts (R, B)))
    with S = n_drafts + 1; n_rounds == 0 (no live decode slot) skips the
    decode half and returns R = 0 outputs.
    """
    rng_p, rng_d = jax.random.split(rng)
    state, ptoks, plps = _prefill_core(
        params, state, chunk, g_lens, g_tables, sample_at, slot_ids,
        prompt_rows, prompt_lens, rng_p, samp_rows_g, orig_lens,
        count_mask, gid_g, gstate0_g, grammar, lora, aid_g,
        draft_params, widths, scatter_mask,
        cfg=cfg, infer_cfg=infer_cfg, scatter_prompt=scatter_prompt,
        mesh=mesh, draft_cfg=draft_cfg, use_rows=use_rows_p,
        use_bias=use_bias_p)
    s = n_drafts + 1
    if n_rounds == 0:
        b = lengths.shape[0]
        out = (jnp.zeros((0, b, s), jnp.int32),
               jnp.zeros((0, b, s), jnp.float32),
               jnp.zeros((0, b), jnp.int32))
        return state, ptoks, plps, lengths, last_token, out
    if n_drafts > 0:
        state, lengths, last, out = _spec_core(
            params, state, lengths, tables, last_token, live, stop_len,
            rng_d, samp_rows_b, gid_b, grammar, lora, aid_b,
            draft_params, slot_ids_d, draft_limit,
            cfg=cfg, infer_cfg=infer_cfg, n_rounds=n_rounds,
            n_drafts=n_drafts, mesh=mesh, draft_cfg=draft_cfg,
            use_rows=use_rows_d, use_bias=use_bias_d)
    else:
        state, lengths, last, (dtoks, dlps, dcnts) = _decode_plain_core(
            params, state, lengths, tables, last_token, live, rng_d,
            samp_rows_b, gid_b, grammar, lora, aid_b, slot_ids_d,
            cfg=cfg, infer_cfg=infer_cfg, n_rounds=n_rounds, mesh=mesh,
            use_rows=use_rows_d, use_bias=use_bias_d)
        out = (dtoks[:, :, None], dlps[:, :, None], dcnts)
    return state, ptoks, plps, lengths, last, out


# ---------------------------------------------------------------------------
# Host-side scheduler
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Slot:
    req: Request
    prompt: list[int]           # admission prompt (original + any tokens
    #                             generated before a preemption)
    pages: list[int]            # chain so far, shared prefix first
    shared_len: int
    stop_len: int               # prompt + max_new (absolute positions)
    admit_seq: int = 0          # admission order — preemption picks max


@dataclasses.dataclass
class _AdmitJob:
    """An in-flight chunked admission: one bucketed group of slots
    (alternating scheduler) or ONE slot with token-granular progress
    (mixed scheduler — `done` advances by whatever width the budget
    granted that iteration, so chunk_w/n_chunks are unused there)."""

    slots: list[int]
    chunk_w: int
    n_chunks: int
    rows: np.ndarray               # (G, n_chunks*chunk_w) remainder tokens
    rem_lens: np.ndarray           # (G,) true remainder lengths
    base_lens: np.ndarray          # (G,) shared_len per row
    prompt_rows: np.ndarray        # (G, prompt_bucket)
    prompt_lens: np.ndarray        # (G,)
    toks: np.ndarray               # captured first-token candidates
    lps: np.ndarray
    got: np.ndarray                # bool — sample captured yet
    next_chunk: int = 0
    done: int = 0                  # mixed: remainder tokens prefilled
    # async scheduler: remainder tokens DISPATCHED (committed done +
    # whatever the in-flight dispatch carries). The overlap planner
    # selects chunks from this cursor so a launch-ahead iteration can
    # never re-prefill tokens already in flight; `done` catches up at
    # each commit, and the two are equal whenever nothing is in flight.
    planned: int = 0


@dataclasses.dataclass
class _Plan:
    """An immutable-by-convention PLANNED iteration (async scheduler):
    everything the launch needs, built against the planned frame while
    the previous dispatch runs. The only fields `_launch_plan` rewrites
    post-commit are the data-dependent decode inputs (d_lens / d_last /
    d_tables / live_g — a handful of (rows,) gathers from the
    just-committed ledger); every policy decision and every other
    array is frozen here."""

    kind: str                       # "mixed" | "decode"
    sel: list                       # [(job, take, d0)] — empty for decode
    activating: list                # slot ids whose admission completes
    n_rounds: int
    win: int                        # g_iter + 1
    g_iter: int
    spec_lens: list | None
    live_ids: np.ndarray
    sl_d: np.ndarray | None
    live_g: np.ndarray
    d_lens: np.ndarray
    d_tables: np.ndarray
    d_last: np.ndarray
    d_stop: np.ndarray
    samp_d: object
    gid_d: np.ndarray
    aid_d: np.ndarray
    owners: list                    # _Slot per live row (identity guard)
    pf: dict | None                 # prefill-half arrays (mixed only)
    scatter_prompt: bool
    use_rows_p: bool
    use_bias_p: bool
    use_rows_d: bool
    use_bias_d: bool
    use_grammar: bool
    use_lora: bool
    stats: dict
    spans: list


@dataclasses.dataclass
class _Inflight:
    """One launched-but-uncommitted dispatch (async scheduler): the
    device futures plus exactly the host context `_commit_inflight`
    needs to scatter the synced results back — and the deterministic
    effects (`activating`, per-row upper bounds via n_rounds*win) the
    NEXT plan's frame is built from."""

    kind: str
    futures: tuple
    sel: list
    activating: list
    live_ids: np.ndarray
    owners: list
    n_rounds: int
    win: int
    g_iter: int
    spec_lens: list | None
    stats: dict
    spans: list
    t_launch: float


class PagedInferenceServer:
    """Continuous-batching server over the paged KV cache.

    Same client API as `InferenceServer` (submit / generate / step /
    start / stop / run_until_idle); see the module docstring for what
    changes inside.
    """

    def __init__(self, params, cfg: ModelConfig, infer_cfg: InferConfig, *,
                 max_slots: int = 8, max_context: int = 1024,
                 page_size: int = 128, num_pages: int | None = None,
                 prompt_buckets: Sequence[int] | None = None,
                 decode_chunk: int = 8, spec_drafts: int = 0,
                 prefill_chunk: int = 256, seed: int = 0,
                 mesh=None, tp_axis: str = "tp",
                 allocation: str = "ondemand",
                 draft_params=None, draft_cfg: ModelConfig | None = None,
                 tokenizer=None, max_pending: int | None = None,
                 admit_decode_chunk: int | None = 1,
                 scheduler: str | None = None,
                 mixed_token_budget: int | None = None,
                 metrics: ServingMetrics | None = None,
                 flight_recorder_size: int | None = None,
                 qos=None, tracing=None, slo=None, spec_control=None,
                 iteration_profile=None, faults=None, brownout=None,
                 anomaly=None, overlap: bool | None = None):
        from cloud_server_tpu.models.quantization import QTensor
        target = jnp.dtype(cfg.dtype)

        def cast_leaf(w):
            if isinstance(w, QTensor):
                return w
            if getattr(w, "dtype", None) == jnp.float32 and w.ndim >= 1:
                return w.astype(target)
            return w

        self.params = jax.tree.map(
            cast_leaf, params, is_leaf=lambda x: isinstance(x, QTensor))
        self.cfg = cfg
        self.infer_cfg = infer_cfg
        self.max_slots = max_slots
        self.page_size = page_size
        self.spec_drafts = spec_drafts
        self.decode_chunk = max(1, decode_chunk)
        self.window = spec_drafts + 1  # kv slack per decode round
        if max_context % page_size:
            raise ValueError(f"{max_context=} must be a multiple of "
                             f"{page_size=}")
        if (cfg.decode_attention_impl == "pallas"
                and jax.default_backend() == "tpu" and page_size % 128):
            # fail at construction, not at the first dispatch — the TPU
            # kernel's manual-DMA slices tile the minor dim by 128
            raise ValueError(
                f"page_size={page_size} must be a multiple of 128 for the "
                "pallas decode path on TPU")
        self.max_context = max_context
        self.max_pages_per_slot = max_context // page_size
        if num_pages is None:
            # default: the same HBM the contiguous layout would reserve
            num_pages = max_slots * self.max_pages_per_slot
        self.allocator = BlockAllocator(num_pages, page_size)
        self.prefill_chunk = max(page_size, min(prefill_chunk, max_context))
        if self.prefill_chunk % page_size:
            raise ValueError("prefill_chunk must be a page multiple")
        if prompt_buckets is None:
            prompt_buckets = _pow2_buckets(16, max_context)
        self.prompt_buckets = sorted(prompt_buckets)
        # continuations (preempted requests re-admitted with their
        # generated tokens appended) can exceed the client-facing
        # buckets, so admission sizing always has max_context available
        self._admit_buckets = sorted(set(self.prompt_buckets)
                                     | {max_context})
        # remainders bucket to a pow2 <= prefill_chunk (single-chunk jobs)
        # or a prefill_chunk multiple (multi-chunk jobs) — chunk WIDTHS
        # stay a small fixed set, chunk COUNTS are host-side loops
        self._rem_buckets = _pow2_buckets(16, self.prefill_chunk)

        # Tensor-parallel serving: the XLA side needs only the params'
        # NamedShardings (jit propagates). The mesh is kept for two
        # things — sharding the page pools on their kv-head axis so the
        # layout is intentional rather than inferred, and running the
        # pallas kernel under shard_map (it cannot be auto-partitioned).
        self.mesh = mesh
        self.tp_axis = tp_axis
        tp = 1 if mesh is None else int(mesh.shape.get(tp_axis, 1))
        if tp > 1 and cfg.num_kv_heads % tp:
            raise ValueError(
                f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads} "
                "for tensor-parallel paged serving")

        # In-server draft-model speculation: a second (small) model
        # drafts against its OWN paged pools, indexed by the SAME page
        # tables/chains — one allocator covers both, and shared prefix
        # pages carry draft kv alongside the target's.
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError("pass draft_params and draft_cfg together")
        if draft_cfg is not None and spec_drafts <= 0:
            raise ValueError("a draft model needs spec_drafts > 0")
        if draft_cfg is not None and draft_cfg.vocab_size != cfg.vocab_size:
            # fail at construction — a mismatch otherwise only explodes
            # (shape error in the accept rule) at the first speculative
            # dispatch, taking every in-flight request with it
            raise ValueError(
                f"draft vocab_size={draft_cfg.vocab_size} != target "
                f"vocab_size={cfg.vocab_size}; speculative verification "
                "compares their token distributions elementwise")
        self.draft_cfg = draft_cfg
        self.draft_params = (None if draft_params is None else jax.tree.map(
            cast_leaf, draft_params,
            is_leaf=lambda x: isinstance(x, QTensor)))

        cache = paged_engine.init_paged_cache(
            cfg, num_pages=num_pages, page_size=page_size, batch=max_slots,
            max_pages_per_slot=self.max_pages_per_slot)
        # per-request sampling penalty state ("prompt_mask" /
        # "out_counts", (B, V) per slot) is NOT allocated here — the
        # first admission that needs penalties materializes it
        # (_ensure_penalty_state), so penalty-free serving never pays
        # its HBM or scatter cost
        self.state = {
            "pools": _split_cache(cache),
            "hist": jnp.zeros((max_slots, max_context), jnp.int32),
            # per-slot grammar DFA state (constrained decoding); slots
            # without a grammar sit at state 0 of the identity grammar
            "gstate": jnp.zeros((max_slots,), jnp.int32),
        }
        if draft_cfg is not None:
            dcache = paged_engine.init_paged_cache(
                draft_cfg, num_pages=num_pages, page_size=page_size,
                batch=max_slots,
                max_pages_per_slot=self.max_pages_per_slot)
            self.state["draft_pools"] = _split_cache(dcache)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ax = tp_axis if tp > 1 else None
            if (tp > 1 and draft_cfg is not None
                    and draft_cfg.num_kv_heads % tp):
                raise ValueError(
                    f"tp={tp} must divide the draft model's num_kv_heads="
                    f"{draft_cfg.num_kv_heads} too")

            def put(x, spec):
                return jax.device_put(x, NamedSharding(mesh, spec))

            def shard_pools(pools):
                return {
                    name: put(pool,
                              P(None, None, ax, None, None)
                              if pool.ndim == 5 else P(None, None, ax, None))
                    for name, pool in pools.items()}

            self.state = {
                name: (shard_pools(val) if name.endswith("pools")
                       else put(val, P()))
                for name, val in self.state.items()}
        # host-authoritative scheduling state
        self.tables = np.full((max_slots, self.max_pages_per_slot),
                              num_pages, np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self.active = np.zeros((max_slots,), bool)
        self.last_token = np.zeros((max_slots,), np.int32)
        self.stop_len = np.zeros((max_slots,), np.int32)
        # per-slot sampling parameter rows (numpy, set at admission) and
        # which slots actually need the device rows path
        self.samp_rows = make_rows([None] * max_slots, infer_cfg,
                                   [0] * max_slots)
        self._needs_rows = np.zeros((max_slots,), bool)
        self._has_bias = np.zeros((max_slots,), bool)
        # regex-constrained decoding: registry of compiled token-DFAs
        # stacked into one device table; per-slot grammar id + the DFA
        # state to resume from at (re-)admission
        self.tokenizer = tokenizer
        # multi-LoRA serving: stacked adapter set + per-slot adapter ids
        from cloud_server_tpu.inference.multi_lora import AdapterSet
        self.adapters = AdapterSet(cfg, mesh=mesh)
        self._aid = np.zeros((max_slots,), np.int32)
        self._grammar_cache = None  # lazy GrammarCache
        self._patterns: list[str] = []
        self._pattern_gid: dict[str, int] = {}
        self._grammar_dev = None  # (tables (Gn,S,V) i32, accept (Gn,S))
        self._gid = np.zeros((max_slots,), np.int32)
        self._gstate0 = np.zeros((max_slots,), np.int32)
        self.orig_len = np.zeros((max_slots,), np.int32)
        self._host_rng = np.random.default_rng(seed)

        # Page-allocation policy:
        #   "ondemand" (default) — admission reserves only the prompt +
        #     one decode window; decode dispatches extend each live
        #     slot's chain just-in-time. On pool exhaustion the YOUNGEST
        #     slot is preempted: its pages release into the radix cache
        #     (content-keyed, fully written — valid KV), its request
        #     requeues as a continuation (prompt + generated so far),
        #     and re-admission re-prefills almost entirely from cache.
        #     Worst-case max_new headroom is never parked, so sustained
        #     concurrency is higher at equal HBM.
        #   "reserve" — the r3 behavior: the whole chain (prompt +
        #     max_new + window slack) reserved at admission; no
        #     mid-flight preemption, lower host bookkeeping.
        if allocation not in ("ondemand", "reserve"):
            raise ValueError(f"unknown allocation policy: {allocation!r}")
        self.allocation = allocation

        # speculative-efficiency counters: committed tokens per model
        # round (mean accepted length + 1); plain decode reports ~1.0
        self.decode_rounds = 0
        self.decode_tokens_committed = 0
        # speculation accounting: tokens drafted on committing rows'
        # behalf (each row's own draft length per round) vs the drafts
        # that actually committed — the wasted-work ledger the adaptive
        # controller and the per-tenant QoS counters read from
        self.spec_tokens_drafted = 0
        self.spec_tokens_accepted = 0
        # adaptive draft-length control (inference/spec_control.py):
        # host-side, fed by the per-round counts the scheduler syncs
        # anyway — zero extra dispatches or syncs (regression-tested).
        # None = fixed spec_drafts length (spec_control=False / "off",
        # or no speculation at all)
        self.spec_control = resolve_controller(
            spec_control, infer_cfg.spec_control_config, spec_drafts,
            has_draft_model=draft_cfg is not None)
        self.tokens_emitted = 0  # lifetime emitted tokens (bench/metrics)
        self.preemptions = 0
        self._admit_seq = 0
        # request-lifecycle telemetry (histograms + counters, observed
        # at host moments the scheduler already owns — zero extra syncs,
        # guarded by tests/test_observability.py's dispatch-count test)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.metrics.registry.add_collector(self._collect_metrics)
        self.tracer = _StepTracer()  # /debug/trace on-demand profiling
        # scheduler flight recorder: one record per busy iteration
        # (token-budget utilization, prefill/decode split, occupancy,
        # compaction, preemptions) for post-mortem churn debugging
        fr_size = (flight_recorder_size if flight_recorder_size is not None
                   else infer_cfg.flight_recorder_size)
        self.flight = FlightRecorder(fr_size)
        self._iter_stats: dict = {}
        # iteration-phase profiler (inference/iteration_profile.py):
        # per-phase host-gap attribution of every busy iteration —
        # pure host-side clock marks at boundaries the scheduler
        # already crosses, zero extra dispatches/syncs (the
        # dispatch-count regression test runs a profiling-enabled
        # clone, and the clock-read count per mixed iteration is
        # asserted constant). None (iteration_profile=False / config
        # off) keeps the exact pre-profiler two-read clock behavior.
        from cloud_server_tpu.inference.iteration_profile import (
            register_phase_hists, resolve_profiler)
        self._profiler = resolve_profiler(iteration_profile,
                                          infer_cfg.iteration_profile)
        # eager per-phase histogram registration: the families exist
        # (and the docs drift check sees them) before any traffic, and
        # the per-iteration observe path is a dict lookup, not a
        # registry get-or-create
        self._phase_hists = ({} if self._profiler is None else
                             register_phase_hists(self.metrics.registry))
        # cache/memory observability (inference/cache_telemetry.py):
        # the allocator's ledger gets the registry's fixed-ladder
        # histogram families (chain depth per walk, page age at
        # eviction, per-iteration evictable fraction) — eager
        # registration, same rationale as the phase histograms; the
        # observe paths are a dict lookup + Histogram.observe, zero
        # dispatches/syncs (the dispatch-count clone covers a
        # QoS+cache-telemetry server)
        from cloud_server_tpu.inference.cache_telemetry import (
            register_cache_hists)
        self._cache_hists = register_cache_hists(self.metrics.registry)
        self.allocator.telemetry.attach_hists(self._cache_hists)
        # idle-iteration visibility: a dead scheduler and an idle one
        # must not look identical from /stats — an idle one keeps
        # incrementing idle_iterations while last_busy_ts ages, a dead
        # one freezes both. Plain int/float writes on the scheduler
        # thread; mirrored on the scrape path only.
        self.idle_iterations = 0
        self.last_busy_ts = 0.0
        # per-request distributed tracing + per-class SLO tracking
        # (inference/request_trace.py, inference/slo.py): both None
        # unless configured — every guarded call site short-circuits,
        # so the scheduler is byte-identical to the pre-trace build.
        # All span timestamps reuse the iteration t0/now pair the
        # flight recorder already reads: zero extra dispatches/syncs
        # (the dispatch-count regression test covers a tracing+SLO
        # clone at 100% sampling).
        from cloud_server_tpu.inference.request_trace import (
            resolve_recorder)
        from cloud_server_tpu.inference.slo import resolve_slo
        self.trace_recorder = resolve_recorder(
            tracing, infer_cfg.trace_sample_rate,
            capacity=infer_cfg.trace_capacity,
            tail_capacity=infer_cfg.trace_tail_capacity)
        self.slo = resolve_slo(slo, infer_cfg.slo_config)
        if self.slo is not None:
            self.metrics.slo = self.slo
        # anomaly watchdog (inference/anomaly.py): online rule engine
        # fed from host state the scheduler already owns — the
        # per-iteration feed is caller-passed clocks and int deltas,
        # zero extra dispatches/syncs (the dispatch-count regression
        # test covers an armed watchdog + tail-retention clone). None
        # unless configured; every call site short-circuits.
        from cloud_server_tpu.inference.anomaly import resolve_anomaly
        self._anomaly = resolve_anomaly(anomaly, infer_cfg.anomaly_config)
        if self._anomaly is not None:
            self._anomaly.bind_slo(self.slo)
        # one-shot forensic debug bundles: bounded ring of auto-captured
        # JSON artifacts (bundle_on_anomaly), plus GET /debug/bundle
        self._bundle_on_anomaly = bool(infer_cfg.bundle_on_anomaly)
        self._bundles: collections.deque = collections.deque(maxlen=8)
        self._bundles_captured = 0
        # per-iteration prefix-cache delta baseline for the watchdog's
        # cache-collapse signal (lifetime counters diffed on the
        # scheduler thread; plain int reads)
        self._anomaly_cache_base = (0, 0)
        # iteration-granular spans staged by the dispatch paths and
        # stamped with the shared (t0, now, iteration) frame by
        # _record_iteration — one list append per traced participant
        self._iter_spans: list = []

        self._slots: list[_Slot | None] = [None] * max_slots
        self._jobs: list[_AdmitJob] = []
        self._pending: collections.deque[Request] = collections.deque()
        # backpressure: submit() past this bound raises QueueFullError
        # (HTTP 429) instead of growing host memory without limit;
        # None = unbounded (library use, trusted callers)
        self.max_pending = max_pending
        # multi-tenant QoS (inference/qos.py): a TenantRegistry, a
        # config dict / JSON string / file path, or None (falls back to
        # InferConfig.qos_config). None disables QoS entirely — every
        # guarded call site below short-circuits, so the scheduler is
        # byte-identical to the pre-QoS FIFO/youngest-preemption paths
        # (pinned by tests/test_mixed_scheduler.py and test_qos.py's
        # single-tenant parity test). All QoS decisions run on host
        # state the scheduler already owns: zero extra dispatches or
        # host syncs (the dispatch-count regression tests cover a
        # QoS-enabled server too).
        from cloud_server_tpu.inference.qos import resolve_registry
        self.qos = resolve_registry(qos, infer_cfg.qos_config)
        # failure-domain layer (inference/faults.py): deterministic
        # fault injection + overload brownout. Both None unless
        # configured — every guarded call site short-circuits, so the
        # scheduler is byte-identical to the pre-fault build (the
        # dispatch/device_get-count regression clones pin it, incl. a
        # clone with a never-firing plan + brownout armed).
        from cloud_server_tpu.inference.faults import (resolve_brownout,
                                                       resolve_fault_plan)
        self._faults = resolve_fault_plan(faults, infer_cfg.fault_plan)
        self._brownout = resolve_brownout(brownout,
                                          infer_cfg.brownout_config)
        if self._brownout is not None and self.qos is None:
            raise ValueError(
                "brownout needs a QoS registry: shed sets are priority "
                "classes, and without tenants nothing can be shed")
        # live request migration (inference/migration.py): the ledger
        # is always present — its record hooks are int adds under a
        # leaf lock, and the migration counter families must exist
        # (zeros) for the docs drift check whether or not a migration
        # ever runs
        from cloud_server_tpu.inference.migration import MigrationLedger
        self._migration = MigrationLedger()
        # _fail_all teardown accounting: how many times the bounded
        # _step_lock acquire timed out and teardown proceeded
        # UNSERIALIZED against a wedged scheduler (the
        # cloud_server_unserialized_teardown_total counter; the
        # timeout is an attribute so the wedged-step test does not
        # wait out the production default)
        self.unserialized_teardowns = 0
        self._teardown_lock_timeout_s = 5.0
        self._draining = False
        # admission-latency bound: while prefill jobs are in flight,
        # decode dispatches shrink to this many rounds (default 1) so a
        # prompt landing mid-decode waits ~one round — not a full
        # decode_chunk burst — between each of its prefill chunks.
        # TTFT p95 is set by this knob; steady-state throughput is not
        # (decode_chunk applies whenever no admission is running).
        # None disables the shrink (r4 behavior).
        if admit_decode_chunk is not None and admit_decode_chunk < 1:
            raise ValueError("admit_decode_chunk must be >= 1 or None")
        self.admit_decode_chunk = admit_decode_chunk
        # Scheduler under admission churn (steady-state decode always
        # uses the multi-round decode dispatch):
        #   "mixed" (default) — stall-free token-budget batching: every
        #     iteration fuses all live decode rows and as many
        #     prefill-chunk tokens as fit under `mixed_token_budget`
        #     into ONE ragged window_forward, so decodes never stall
        #     behind a prefill dispatch and admissions never wait out a
        #     decode dispatch.
        #   "alternating" — the r5 behavior (separate prefill-chunk and
        #     decode dispatches per step); kept as the fallback. Both
        #     speculation sources (n-gram AND draft-model) run under
        #     either scheduler: the draft model's prefill/decode
        #     discipline is fused into `_mixed_step`.
        sched = scheduler if scheduler is not None else infer_cfg.scheduler
        if sched not in ("mixed", "alternating"):
            raise ValueError(f"unknown scheduler: {sched!r}")
        self.scheduler = sched
        self._mixed_enabled = sched == "mixed"
        budget = (mixed_token_budget if mixed_token_budget is not None
                  else infer_cfg.mixed_token_budget)
        if budget is None or budget <= 0:
            # auto: effectively work-conserving — a full decode burst
            # plus a full chunk for every slot fits, so the budget only
            # bites when set explicitly. Lower it to trade admission
            # speed for a per-iteration latency (ITL) bound.
            budget = max_slots * (self.window * self.decode_chunk
                                  + self.prefill_chunk)
        if budget < self.window:
            raise ValueError(
                f"mixed_token_budget={budget} cannot fit one decode "
                f"window ({self.window} tokens)")
        self.mixed_token_budget = int(budget)
        # dispatch-width buckets for the mixed path (compile-cache bound)
        self._mixed_buckets = sorted(
            set(_pow2_buckets(16, self.prefill_chunk))
            | {_pad_pow2(self.window)})
        self._lock = threading.Lock()
        # submit() notifies this condition (same mutex as _lock) so
        # an idle serve_forever parks in a bounded wait instead of
        # busy-polling — new work wakes it immediately (cancel needs
        # no notify: an idle-waiting scheduler implies nothing left
        # to cancel); stop() notifies for prompt shutdown
        self._work = threading.Condition(self._lock)
        self._step_lock = threading.Lock()
        # Async double-buffered scheduling (the module docstring's
        # overlap section): mixed scheduler only — the alternating
        # scheduler keeps its sequential per-chunk loop.
        ov = infer_cfg.overlap if overlap is None else bool(overlap)
        self.overlap = bool(ov)
        self._overlap_enabled = self.overlap and self._mixed_enabled
        self._inflight: _Inflight | None = None
        # deferred sweep reaps: (slot_id, _Slot, reason) marked while a
        # dispatch is in flight; released right after its commit
        self._reaped: list[tuple[int, _Slot, str]] = []
        # disaggregated prefill/decode handoff (the ReplicatedRouter's
        # role-specialized fleets): requests whose chunked prefill
        # completed THIS iteration and that carry a submit-time
        # `handoff=` callback queue here; step() fires the callbacks
        # AFTER releasing _step_lock (the callback typically enqueues a
        # migrate_export, which needs that lock). Scheduler-thread-only
        # state — appended under _step_lock, drained on the same thread
        # right after it is released.
        self._handoff_ready: list[Request] = []
        # request_id -> (page_ids, device gathers with their host
        # copies already started): KV prefetched by _handoff_prefetch
        # BEFORE the final prefill chunk's dispatch (donation
        # invalidates the pools after launch), consumed by
        # _export_request_locked so the handoff export pays only the
        # pages the last chunks wrote. Popped on export or request
        # completion, whichever comes first.
        self._handoff_stash: dict[str, tuple[tuple[int, ...], dict]] = {}
        # perf_counter stamp of the launch performed THIS iteration
        # (consumed by _record_iteration into the flight record's
        # t_launch — the Perfetto inflight track's left edge)
        self._iter_launch_ts: float | None = None
        self._rng = jax.random.key(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- client API ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: int | None = None, stream=None,
               sampling: SamplingParams | None = None,
               adapter: str | None = None,
               tenant: str | None = None,
               trace_ctx: tuple | None = None,
               deadline_s: float | None = None,
               fail_handler=None, handoff=None,
               _migration=None) -> Request:
        if self._stop.is_set():
            raise RuntimeError("server is stopped; not accepting requests")
        if self._faults is not None:
            self._faults.check("submit_reject")
        if deadline_s is not None and not (
                math.isfinite(deadline_s) and deadline_s > 0):
            # `not (x > 0)` rather than `x <= 0`: NaN compares False
            # BOTH ways and would otherwise slip through as a silent
            # never-expiring deadline
            raise ValueError("deadline_s must be a finite positive "
                             "number of seconds")
        if (adapter is not None
                and self.adapters.adapter_id(adapter) is None):
            raise ValueError(
                f"unknown adapter {adapter!r}; registered: "
                f"{self.adapters.names}")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        _bucket(len(prompt), self.prompt_buckets)  # raises if too long
        max_new = (self.infer_cfg.max_decode_len if max_new_tokens is None
                   else max_new_tokens)
        # leave room for the last speculative window's writes
        max_new = min(max_new, self.max_context - len(prompt) - self.window)
        if max_new <= 0:
            raise ValueError(
                f"prompt of {len(prompt)} tokens leaves no room to decode "
                f"within max_context={self.max_context}")
        if sampling is not None and sampling.regex is not None:
            if self.infer_cfg.eos_token_id < 0:
                raise ValueError(
                    "regex-constrained decoding needs eos_token_id >= 0 "
                    "(completion is signalled by EOS at an accepting "
                    "state)")
            self._grammar_gid(sampling.regex)  # compile now; 400 here
        if self.qos is not None:
            tenant = self.qos.resolve(tenant)
            if self._brownout is not None and _migration is None:
                # overload brownout: shed this class's admissions with
                # (migration continuations are exempt: the stream's
                # tokens are already paid for and delivered — shedding
                # one loses strictly more work than it saves)
                # a jittered Retry-After (429) while the detector
                # grades the replica overloaded — interactive traffic
                # keeps its SLO instead of every class degrading
                cls = self.qos.priority_class(tenant)
                if self._brownout.shed(cls):
                    from cloud_server_tpu.inference.faults import (
                        BrownoutShedError)
                    raise BrownoutShedError(
                        f"overloaded: shedding {cls!r} admissions "
                        "(brownout); retry later", tenant=tenant,
                        priority_class=cls,
                        retry_after_s=self._brownout.retry_hint())
        else:
            # no registry = no frozen tenant set to bound cardinality:
            # a caller-supplied string must not mint per-tenant labeled
            # metric series (observe_emit labels by req.tenant)
            tenant = None
        req = Request(prompt=list(prompt), max_new_tokens=max_new,
                      stream=stream, sampling=sampling, adapter=adapter,
                      tenant=tenant,
                      seed_used=(_migration.seed_used
                                 if _migration is not None else
                                 resolve_seed(sampling, self._host_rng,
                                              self._lock)),
                      submit_time=time.perf_counter())
        if _migration is not None:
            # migration continuation (inference/migration.py): resume
            # another replica's stream. The generated state is filled
            # in BEFORE the append below makes the request visible to
            # the scheduler, which then admits it as a CONTINUATION
            # (admission prompt = prompt + tokens, the preemption-
            # resume path) and decode picks up at the exact next
            # token. seed_used above is the SOURCE's seed: RNG streams
            # are position-keyed, so seed + token index reproduces
            # every future draw exactly — no generator state crosses.
            req.tokens = list(_migration.tokens)
            req.logprobs = list(_migration.logprobs)
            req.emit_times = list(_migration.emit_times)
        if deadline_s is None and self.qos is not None:
            # per-QoS-class default deadline (None when the tenant's
            # class declares none)
            deadline_s = self.qos.default_deadline(tenant)
        if deadline_s is not None:
            req.deadline = req.submit_time + float(deadline_s)
        if self.slo is not None:
            # class mapping: the tenant's QoS priority class; plain
            # "default" without a registry
            req.slo_class = (self.qos.priority_class(tenant)
                             if self.qos is not None else None)
        # the router's failover hook rides in THROUGH submit (not
        # installed after it returns): once the request is in the
        # pending queue any scheduler crash may complete it, and a
        # hook landing late would miss its own failure
        req._fail_handler = fail_handler
        # disaggregated handoff callback (role-specialized fleets):
        # fired once, outside _step_lock, when this request's chunked
        # prefill completes with decode budget left — the router's
        # hook migrates it to a decode replica. Rides IN through
        # submit for the same reason fail_handler does.
        req._handoff = handoff
        req._on_cancel = self._handle_cancel  # before it can be seen
        with self._lock:
            # under the lock: drain() flips _draining under the same
            # lock, so a submit either lands before drain observes the
            # queue or is rejected — never appended-then-abandoned
            if self._draining:
                raise RuntimeError(
                    "server is draining; not accepting requests")
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                raise QueueFullError(
                    f"pending queue is full ({self.max_pending} requests);"
                    " retry later")
            if self.qos is not None:
                # per-tenant backpressure AFTER the global bound: one
                # tenant at its pending cap or out of prompt-bucket
                # budget 429s while every other tenant keeps admitting.
                # On failure nothing was mutated for this request; on
                # success the tenant's pending count advances atomically
                # with the append below. A migration continuation bills
                # ZERO prompt tokens: its prompt was already charged on
                # the source replica and its salvaged tokens were never
                # prompt tokens — re-billing would double-charge the
                # tenant fleet-wide for one request.
                self.qos.gate_submit(
                    tenant, len(prompt),
                    charge_tokens=0 if _migration is not None else None)
            # telemetry BEFORE the append: once the request is in the
            # queue the scheduler thread may admit (even finish) it, and
            # the timeline must stay in lifecycle order. The trace
            # opens here too — AFTER every rejection path above, so a
            # refused submit (queue full, tenant 429, draining) can
            # never leak into the recorder's live set, and before the
            # append, so the scheduler cannot finish the request ahead
            # of its trace existing.
            if self.trace_recorder is not None:
                tr = self.trace_recorder.begin(req, trace_ctx)
                if tr is not None and tenant is not None:
                    tr.annotate(tenant=tenant)
            req.record_event("submit", req.submit_time)
            self.metrics.observe_submit(req)
            self._pending.append(req)
            # wake an idle scheduler thread parked on the bounded
            # condition wait (serve_forever) — submit latency must not
            # pay the idle-wait timeout
            self._work.notify()
        return req

    def _handle_cancel(self, req: Request) -> None:
        """Client-thread half of Request.cancel(): a request still in
        the pending queue finishes here, immediately. One that is
        already admitted (slot or admission job) is reaped by the
        scheduler's sweep at the start of the next step()."""
        with self._lock:
            try:
                self._pending.remove(req)
            except ValueError:
                return  # admitted: the step sweep owns the teardown
            if self.qos is not None:
                self.qos.on_pending_removed(req.tenant)
        req.finish_reason = "cancelled"
        self._complete(req)

    def _complete(self, req: Request) -> None:
        """Terminal bookkeeping for any request leaving the server:
        observe lifecycle metrics, then unblock waiters. Every path
        that ends a request (finish / cancel / fail) goes through here
        so the telemetry can never miss a terminal state.

        Failure interception: a request completing with an "error:"
        reason is offered to its `_fail_handler` (installed by the
        ReplicatedRouter at submit) AFTER the telemetry — the failure
        really happened here — but BEFORE `_done`: a True return means
        a failover retry on another replica now owns completion, so
        waiters stay blocked until the retry finishes and mirrors its
        outcome back."""
        now = self.metrics.observe_finish(req)
        if self._anomaly is not None:
            ttft = (req.emit_times[0] - req.submit_time
                    if req.emit_times and req.submit_time is not None
                    else None)
            itl = (None if len(req.emit_times) < 2 else
                   (req.emit_times[-1] - req.emit_times[0])
                   / (len(req.emit_times) - 1))
            fired = self._anomaly.observe_request(
                now=now, ttft_s=ttft, itl_s=itl,
                finish_reason=req.finish_reason)
            if fired:
                self._on_anomaly(fired)
        # analysis: allow[lock-discipline] GIL-atomic dict pop: drop
        # any unconsumed handoff KV prefetch (the request ended
        # locally before the export fired) — safe from any completing
        # thread, no compound read-modify-write
        self._handoff_stash.pop(req.request_id, None)
        if self.trace_recorder is not None and (
                req.trace is not None or req.tail_trace is not None):
            slo_violated = False
            if req.trace is None and self.slo is not None:
                e2e = (None if req.submit_time is None
                       else now - req.submit_time)
                ttft = (req.emit_times[0] - req.submit_time
                        if req.emit_times and req.submit_time is not None
                        else None)
                slo_violated = (
                    (e2e is not None and self.slo.exceeds_target(
                        req.slo_class, "e2e", e2e))
                    or (ttft is not None and self.slo.exceeds_target(
                        req.slo_class, "ttft", ttft)))
            in_anomaly = (self._anomaly is not None
                          and req.trace is None
                          and self._anomaly.active_count(now) > 0)
            self.trace_recorder.finish(req, slo_violated=slo_violated,
                                       in_anomaly=in_anomaly)
        h = req._fail_handler
        if (h is not None and req.finish_reason is not None
                and req.finish_reason.startswith("error") and h(req)):
            return
        req._done.set()
        cb = req._on_done
        if cb is not None:
            cb(req)

    def generate(self, prompts, *, max_new_tokens=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    def embed(self, prompts: Sequence[Sequence[int]]) -> "np.ndarray":
        """Mean-pooled, L2-normalised sequence embeddings for the base
        model (engine.encode), padded per prompt bucket so repeat calls
        hit the jit cache. Runs under the scheduler lock — it shares
        the device with decode dispatches. Returns (N, embed_dim) f32."""
        from cloud_server_tpu.inference import engine as _engine
        if not prompts:
            return np.zeros((0, self.cfg.embed_dim), np.float32)
        out = np.zeros((len(prompts), self.cfg.embed_dim), np.float32)
        by_bucket: dict[int, list[int]] = {}
        for i, p in enumerate(prompts):
            if len(p) == 0:
                raise ValueError("empty prompt")
            by_bucket.setdefault(_bucket(len(p), self.prompt_buckets),
                                 []).append(i)
        with self._step_lock:
            for pb, idxs in by_bucket.items():
                g = _pad_pow2(len(idxs))  # bound compile cache by shape
                rows = np.full((g, pb), self.infer_cfg.pad_token_id,
                               np.int32)
                lens = np.ones((g,), np.int32)  # padding rows: 1 token
                for r, i in enumerate(idxs):
                    rows[r, :len(prompts[i])] = prompts[i]
                    lens[r] = len(prompts[i])
                vecs = _engine.encode(self.params, jnp.asarray(rows),
                                      jnp.asarray(lens), cfg=self.cfg)
                # analysis: allow[lock-discipline] deliberate sync under
                # _step_lock: embeddings share the device with decode
                # dispatches — serializing on the step lock is the point
                out[idxs] = np.asarray(jax.device_get(vecs))[:len(idxs)]
        return out

    @property
    def num_active(self) -> int:
        # analysis: allow[lock-discipline] racy-by-design monitoring
        # read: len-stable list, GIL-atomic element loads; staleness is
        # bounded by one iteration and only steers placement/idle checks
        return sum(s is not None for s in self._slots)

    @property
    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def pending_prefill_tokens(self) -> int:
        """Prefill tokens this replica still owes: the unprefilled
        remainder of every in-flight admission job plus the full
        admission length of everything queued. The ReplicatedRouter's
        role-aware placement reads this as a PREFILL replica's load
        signal (a 4k-token prompt is not the same backlog as a
        4-token one, which request counts cannot see)."""
        # analysis: allow[lock-discipline] racy-by-design monitoring
        # read of _jobs (scheduler-thread state): list() snapshots the
        # container, element reads are GIL-atomic, staleness is bounded
        # by one iteration and only steers placement
        jobs = list(self._jobs)
        n = sum(max(int(job.rem_lens[0]) - job.done, 0)
                for job in jobs)
        with self._lock:
            n += sum(len(r.prompt) + len(r.tokens)
                     for r in self._pending)
        return n

    def prefix_cache_stats(self):
        """Allocator snapshot (AllocatorStats). Called from the scrape
        path and the router WITHOUT the scheduler locks — audited
        under the lock-discipline passes (LD1–LD4): every field is
        derived from plain ints and `len()`s of containers the
        scheduler thread mutates under `_step_lock`; each read is
        GIL-atomic, so a snapshot can lag the running iteration by a
        few pages but can never tear a single value. Taking
        `_step_lock` here would stall every scrape behind a whole
        dispatch — the same racy-by-design monitoring trade `num_active`
        documents."""
        return self.allocator.stats()

    # -- internals ----------------------------------------------------------

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def add_adapter(self, name: str, lora_params: dict,
                    lora_cfg) -> int:
        """Register a LoRA adapter for per-request serving; returns its
        id. Requests select it via submit(..., adapter=name). Restacks
        the device tensors (one recompile of the dispatch shapes)."""
        if (self.cfg.num_experts >= 2 and
                {"w_gate", "w_up", "w_down"} & set(lora_cfg.targets)):
            raise ValueError(
                "MLP-targeting adapters cannot be served per-request on "
                "an MoE base (expert-stacked MLP); use attention targets "
                "or merged serving")
        with self._lock:
            return self.adapters.add(name, lora_params, lora_cfg)

    def _grammar_gid(self, pattern: str) -> int:
        """Register (compile + restack) a pattern; returns its grammar
        id. Called from submit() so compilation errors surface on the
        CLIENT thread as ValueError, never killing the scheduler."""
        # analysis: allow[lock-discipline] double-checked fast path: a
        # GIL-atomic dict probe; the locked re-check below is authoritative
        gid = self._pattern_gid.get(pattern)
        if gid is not None:
            return gid
        if self.tokenizer is None:
            raise ValueError(
                "regex-constrained requests need a tokenizer: construct "
                "PagedInferenceServer(..., tokenizer=...)")
        from cloud_server_tpu.inference import grammar as _g
        if self._grammar_cache is None:
            self._grammar_cache = _g.GrammarCache(self.tokenizer,
                                                  self.cfg.vocab_size)
        self._grammar_cache.get(pattern)  # compile (raises on bad regex)
        with self._lock:
            if pattern not in self._pattern_gid:
                self._patterns.append(pattern)
                self._pattern_gid[pattern] = len(self._patterns)
                self._rebuild_grammar_stack()
            return self._pattern_gid[pattern]

    def _rebuild_grammar_stack(self) -> None:
        """(Gn, S_max, V) device stack: gid 0 = the identity grammar
        (everything allowed, state stays 0), gid i = pattern i-1. Rows
        past a grammar's state count are DEAD (unreachable)."""
        from cloud_server_tpu.inference import grammar as _g
        dfas = [self._grammar_cache.get(pat) for pat in self._patterns]
        s_max = max([d.num_states for d in dfas] + [1])
        v = self.cfg.vocab_size
        tables = np.full((len(dfas) + 1, s_max, v), _g.DEAD, np.int32)
        accept = np.zeros((len(dfas) + 1, s_max), bool)
        tables[0] = 0
        accept[0] = True
        for i, d in enumerate(dfas, start=1):
            tables[i, :d.num_states] = d.next_state
            accept[i, :d.num_states] = d.accept
        tb, ac = jnp.asarray(tables), jnp.asarray(accept)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            tb = jax.device_put(tb, NamedSharding(self.mesh, P()))
            ac = jax.device_put(ac, NamedSharding(self.mesh, P()))
        self._grammar_dev = (tb, ac)

    def _ensure_penalty_state(self) -> None:
        """Materialize the (B, V) penalty buffers on first need (one-time
        recompile; pre-materialization slots carry neutral penalties,
        for which the buffers are read-irrelevant)."""
        if "prompt_mask" in self.state:
            return
        pm = jnp.zeros((self.max_slots, self.cfg.vocab_size), bool)
        oc = jnp.zeros((self.max_slots, self.cfg.vocab_size), jnp.int32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            pm = jax.device_put(pm, NamedSharding(self.mesh, P()))
            oc = jax.device_put(oc, NamedSharding(self.mesh, P()))
        self.state["prompt_mask"] = pm
        self.state["out_counts"] = oc

    def _emit(self, req: Request, token: int, logprob: float) -> bool:
        n0 = len(req.emit_times)
        done = emit_token(req, token, logprob, self.infer_cfg)
        if not (done and req.finish_reason == "eos"):
            self.tokens_emitted += 1  # stop-truncated tokens still count
            if self.qos is not None:
                # bill the generated token: the tenant's bucket takes
                # the debt (deprioritizing future admissions) and the
                # lifetime counter feeds the fair-share stats
                self.qos.charge_generated(req.tenant)
        if len(req.emit_times) > n0:  # a stop match truncates instead
            self.metrics.observe_emit(req)
        return done

    def _committed(self, slot_id: int) -> list[int]:
        """The slot's committed token stream, truncated to the device's
        written-KV watermark (self.lengths). slot.prompt already folds
        in any tokens generated before a preemption, so only tokens
        generated SINCE this admission are appended. The truncation
        matters at page boundaries: the newest sampled token has no KV
        written yet (its window runs next dispatch), so releasing the
        untruncated stream could key a full page whose final lane is
        garbage — a future prefix hit would serve invalid KV."""
        slot = self._slots[slot_id]
        since = len(slot.prompt) - len(slot.req.prompt)
        stream = slot.prompt + slot.req.tokens[since:]
        return stream[:int(self.lengths[slot_id])]

    def _release_slot(self, slot_id: int, keyed_tokens: list[int]) -> _Slot:
        """The slot-teardown invariant, in ONE place: release the page
        chain (keyed by `keyed_tokens` — pass [] to key nothing), clear
        the slot, sentinel its table row, deactivate. Every path that
        retires a slot (finish, preemption, failure) goes through here;
        what happens to the request afterwards is the caller's story."""
        slot = self._slots[slot_id]
        self.allocator.release(slot.pages, keyed_tokens,
                               namespace=slot.req.adapter or "",
                               tenant=slot.req.tenant)
        self._slots[slot_id] = None
        self.tables[slot_id, :] = self.allocator.num_pages  # sentinel
        self.active[slot_id] = False
        self.lengths[slot_id] = 0
        self._needs_rows[slot_id] = False  # don't pin rows-mode dispatch
        self._has_bias[slot_id] = False
        self._gid[slot_id] = 0
        self._gstate0[slot_id] = 0
        self._aid[slot_id] = 0
        if self.spec_control is not None:
            self.spec_control.on_release(slot_id)
        return slot

    def _finish(self, slot_id: int) -> None:
        slot = self._release_slot(slot_id, self._committed(slot_id))
        self._complete(slot.req)

    # -- admission ----------------------------------------------------------

    def _rem_bucket(self, rem: int) -> int:
        if rem <= self.prefill_chunk:
            return _bucket(rem, self._rem_buckets)
        return -(-rem // self.prefill_chunk) * self.prefill_chunk

    def _start_admissions(self) -> None:
        """Pop pending requests into slots (pages permitting) and build
        bucketed chunked-prefill jobs.

        A request that already carries generated tokens is a
        CONTINUATION (it was preempted): its admission prompt is
        prompt + tokens, so the prefix walk re-hits the pages its
        preemption released into the cache and the sampled first token
        is simply the next token of the stream."""
        staged: list[int] = []
        doomed: list[Request] = []  # impossible requests, completed
        #                             AFTER the lock: _complete may run
        #                             a router fail-handler that takes
        #                             the ROUTER lock, and a router
        #                             thread holding that lock reads
        #                             num_pending (our _lock) — calling
        #                             it here would be an ABBA deadlock
        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
            while self._pending and free:
                if self.qos is not None:
                    # deficit-round-robin over tenants: which pending
                    # request funds the next free slot (FIFO within a
                    # tenant; single-tenant degenerates to index 0 —
                    # exactly the FIFO below)
                    idx = self.qos.next_admission_index(self._pending)
                else:
                    idx = 0
                req = self._pending[idx]
                prompt = list(req.prompt) + list(req.tokens)
                remaining = req.max_new_tokens - len(req.tokens)
                shared, shared_len = self.allocator.lookup_prefix(
                    prompt, namespace=req.adapter or "",
                    tenant=req.tenant)
                if self.allocation == "ondemand":
                    # prompt + one decode window; chains grow per
                    # dispatch in _extend_chains
                    total = len(prompt) + self.window
                else:
                    total = len(prompt) + remaining + self.window
                need = -(-total // self.page_size) - len(shared)
                if (self._faults is not None
                        and self._faults.fire("alloc_famine")
                        is not None):
                    # injected TRANSIENT page famine: release the walk
                    # refs and retry next iteration — exercises the
                    # famine-retry path without shrinking the pool or
                    # permanently failing the request
                    self.allocator.release(shared, prompt[:shared_len],
                                           namespace=req.adapter or "",
                                           tenant=req.tenant)
                    break
                fresh = self.allocator.alloc(max(0, need),
                                             tenant=req.tenant)
                if fresh is None:
                    self.allocator.release(shared, prompt[:shared_len],
                                           namespace=req.adapter or "",
                                           tenant=req.tenant)
                    if self.num_active == 0 and not self._jobs:
                        # nothing running will ever free pages: the pool
                        # is simply too small for this request. Marked
                        # REQUEST-caused: the router must not retry it
                        # (it fails identically on every same-sized
                        # replica) nor count it against the breaker
                        del self._pending[idx]
                        if self.qos is not None:
                            self.qos.on_pending_removed(req.tenant)
                        req.finish_reason = (
                            "error: request needs more pages than the "
                            "pool can ever provide")
                        req._request_fault = True
                        doomed.append(req)
                        continue
                    break
                del self._pending[idx]
                if self.qos is not None:
                    # consume the tenant's DRR deficit only now that
                    # the admission actually succeeded (a page-famine
                    # break above leaves it intact for the retry)
                    self.qos.charge_admission(req.tenant, len(prompt))
                    self.qos.on_pending_removed(req.tenant)
                if shared_len:
                    # REALIZED prefill savings: recorded only once the
                    # admission holds its pages (the walk above already
                    # counted the optimistic hit tokens; a page-famine
                    # release-and-retry must not double-count savings)
                    self.allocator.telemetry.record_saved(req.tenant,
                                                          shared_len)
                slot_id = free.pop(0)
                self._admit_seq += 1
                slot = _Slot(req=req, prompt=prompt,
                             pages=shared + fresh, shared_len=shared_len,
                             stop_len=len(prompt) + remaining,
                             admit_seq=self._admit_seq)
                self._slots[slot_id] = slot
                self.tables[slot_id, :] = self.allocator.num_pages
                self.tables[slot_id, :len(slot.pages)] = slot.pages
                self.lengths[slot_id] = shared_len
                self.stop_len[slot_id] = slot.stop_len
                self.active[slot_id] = False  # live once admission is done
                # per-request sampling rows (seed stable across
                # preemption: seed_used was fixed at submit)
                row = make_rows([req.sampling], self.infer_cfg,
                                [req.seed_used],
                                prompt_lens=[len(req.prompt)])
                for dst, src in zip(self.samp_rows, row):
                    dst[slot_id] = src[0]
                self._needs_rows[slot_id] = (
                    req.sampling is not None
                    and req.sampling.needs_device_rows(self.infer_cfg))
                self._has_bias[slot_id] = (
                    req.sampling is not None
                    and bool(req.sampling.logit_bias))
                if (req.sampling is not None
                        and req.sampling.regex is not None):
                    # direct registry read, NOT _grammar_gid(): that
                    # helper takes _lock — already held here — and
                    # submit() guarantees every admitted request's
                    # pattern is registered (patterns are never
                    # removed, so continuations re-hit it too)
                    self._gid[slot_id] = self._pattern_gid[
                        req.sampling.regex]
                    # continuations resume mid-pattern: replay the
                    # already-generated tokens host-side
                    self._gstate0[slot_id] = self._grammar_cache.get(
                        req.sampling.regex).walk(req.tokens)
                else:
                    self._gid[slot_id] = 0
                    self._gstate0[slot_id] = 0
                self._aid[slot_id] = (
                    0 if req.adapter is None
                    else self.adapters.adapter_id(req.adapter))
                if (req.sampling is not None
                        and req.sampling.needs_penalty_state()):
                    self._ensure_penalty_state()
                self.orig_len[slot_id] = len(req.prompt)
                if self.spec_control is not None:
                    # fresh controller state at the initial draft
                    # length; a continuation re-prefills the draft
                    # cache, so any staleness clears with it
                    self.spec_control.on_admit(slot_id)
                staged.append(slot_id)
        for req in doomed:
            self._complete(req)
        if not staged:
            return
        now = time.perf_counter()  # one clock read per admission burst
        for slot_id in staged:
            self.metrics.observe_admit(self._slots[slot_id].req, now)
        pad_tok = self.infer_cfg.pad_token_id
        if self._mixed_enabled:
            # mixed scheduler: ONE job per slot — progress is
            # token-granular (`done`), widths are chosen per iteration
            # by the token budget, so there is no fixed chunk schedule
            # to share and admissions stay individually preemptible
            for slot_id in staged:
                slot = self._slots[slot_id]
                rem_toks = slot.prompt[slot.shared_len:]
                rb = self._rem_bucket(len(rem_toks))
                pb = _bucket(len(slot.prompt), self._admit_buckets)
                job = _AdmitJob(
                    slots=[slot_id], chunk_w=rb, n_chunks=1,
                    rows=np.full((1, rb), pad_tok, np.int32),
                    rem_lens=np.asarray([len(rem_toks)], np.int32),
                    base_lens=np.asarray([slot.shared_len], np.int32),
                    prompt_rows=np.full((1, pb), pad_tok, np.int32),
                    prompt_lens=np.asarray([len(slot.prompt)], np.int32),
                    toks=np.zeros((1,), np.int32),
                    lps=np.zeros((1,), np.float64),
                    got=np.zeros((1,), bool))
                job.rows[0, :len(rem_toks)] = rem_toks
                job.prompt_rows[0, :len(slot.prompt)] = slot.prompt
                self._jobs.append(job)
            return
        # group by remainder bucket => uniform chunk schedule per job
        by_bucket: dict[int, list[int]] = {}
        for slot_id in staged:
            slot = self._slots[slot_id]
            rb = self._rem_bucket(len(slot.prompt) - slot.shared_len)
            by_bucket.setdefault(rb, []).append(slot_id)
        for rb, slot_ids in by_bucket.items():
            w = min(rb, self.prefill_chunk)
            n_chunks = -(-rb // w)
            g = len(slot_ids)
            pb = _bucket(max(len(self._slots[s].prompt) for s in slot_ids),
                         self._admit_buckets)
            job = _AdmitJob(
                slots=list(slot_ids), chunk_w=w, n_chunks=n_chunks,
                rows=np.full((g, n_chunks * w), pad_tok, np.int32),
                rem_lens=np.zeros((g,), np.int32),
                base_lens=np.zeros((g,), np.int32),
                prompt_rows=np.full((g, pb), pad_tok, np.int32),
                prompt_lens=np.zeros((g,), np.int32),
                toks=np.zeros((g,), np.int32),
                lps=np.zeros((g,), np.float64),
                got=np.zeros((g,), bool))
            for i, sid in enumerate(slot_ids):
                slot = self._slots[sid]
                rem_toks = slot.prompt[slot.shared_len:]
                job.rows[i, :len(rem_toks)] = rem_toks
                job.rem_lens[i] = len(rem_toks)
                job.base_lens[i] = slot.shared_len
                job.prompt_rows[i, :len(slot.prompt)] = slot.prompt
                job.prompt_lens[i] = len(slot.prompt)
            self._jobs.append(job)

    def _run_one_chunk(self, job: _AdmitJob) -> None:
        if self._faults is not None:
            # injected dispatch failure (see _mixed_dispatch)
            self._faults.check("dispatch")
        c = job.next_chunk
        w = job.chunk_w
        g = len(job.slots)
        gp = _pad_pow2(g)  # bound compiles: group rows pad to a power of 2

        def pad_rows(a, fill):
            if g == gp:
                return a
            padded = np.full((gp,) + a.shape[1:], fill, a.dtype)
            padded[:g] = a
            return padded

        st = self._iter_stats  # flight recorder: prefill share per iter
        st.setdefault("scheduler", self.scheduler)
        st["prefill_tokens"] = st.get("prefill_tokens", 0) + w * g
        if self.trace_recorder is not None:
            for sid in job.slots:
                r = self._slots[sid].req
                if r.trace is not None:
                    self._iter_spans.append(
                        (r, "prefill_chunk",
                         {"slot": sid, "tokens": w, "chunk": c}))
        chunk = pad_rows(job.rows[:, c * w:(c + 1) * w],
                         self.infer_cfg.pad_token_id)
        g_lens = pad_rows(job.base_lens + c * w, 0)
        slot_ids = pad_rows(np.asarray(job.slots, np.int32), self.max_slots)
        g_tables = np.full((gp, self.max_pages_per_slot),
                           self.allocator.num_pages, np.int32)
        g_tables[:g] = self.tables[np.asarray(job.slots)]
        sample_at = pad_rows(np.clip(job.rem_lens - 1 - c * w, 0, w - 1), 0)
        in_range = ((job.rem_lens - 1) >= c * w) & (
            (job.rem_lens - 1) < (c + 1) * w)
        prompt_rows = pad_rows(job.prompt_rows, self.infer_cfg.pad_token_id)
        prompt_lens = pad_rows(job.prompt_lens, 0)
        sl = np.asarray(job.slots)
        sl_pad = np.zeros((gp,), np.int64)
        sl_pad[:g] = sl
        samp_g = _gather_samp_rows(self.samp_rows, sl_pad, g)
        orig_lens = pad_rows(self.orig_len[sl], 0)
        count_mask = pad_rows(in_range, False)
        use_rows = bool(self._needs_rows[sl].any())
        use_bias = bool(self._has_bias[sl].any())
        use_grammar = bool((self._gid[sl] > 0).any())
        # analysis: allow[lifecycle-discipline] a raise in the chunk's device work between the span append and the job removal is terminal for the replica — _fail_all clears _jobs and completes every slot, so the pair is never observed torn
        gid_g = jnp.asarray(pad_rows(self._gid[sl], 0))
        gst0_g = jnp.asarray(pad_rows(self._gstate0[sl], 0))
        use_lora = bool((self._aid[sl] > 0).any())
        aid_g = jnp.asarray(pad_rows(self._aid[sl], 0))

        prof = self._profiler
        if prof is not None:
            # per-chunk marks ACCUMULATE into the iteration's phases
            # (the alternating scheduler runs several chunks per step)
            prof.mark("build")
        self.state, toks, lps = _prefill_chunk(
            self.params, self.state, jnp.asarray(chunk),
            jnp.asarray(g_lens, jnp.int32), jnp.asarray(g_tables),
            jnp.asarray(sample_at, jnp.int32), jnp.asarray(slot_ids),
            jnp.asarray(prompt_rows), jnp.asarray(prompt_lens, jnp.int32),
            self._next_rng(), jax.tree.map(jnp.asarray, samp_g),
            jnp.asarray(orig_lens, jnp.int32), jnp.asarray(count_mask),
            gid_g, gst0_g,
            # analysis: allow[lock-discipline] _grammar_dev is rebuilt
            # under _lock at submit/registration time, BEFORE any
            # request using the new gid can reach admission; the
            # scheduler reads one atomically-swapped reference
            self._grammar_dev if use_grammar else None,
            self.adapters.device_args() if use_lora else None, aid_g,
            self.draft_params,
            cfg=self.cfg, infer_cfg=self.infer_cfg,
            scatter_prompt=(c == 0), mesh=self.mesh,
            draft_cfg=self.draft_cfg, use_rows=use_rows,
            use_bias=use_bias)
        # analysis: allow[lock-discipline] THE sanctioned per-iteration
        # host sync — _step_lock serializes the scheduler by design
        # (the dispatch-discipline pass pins the sanctioned set)
        toks, lps = jax.device_get((toks, lps))
        if prof is not None:
            prof.mark("device")
        toks, lps = np.asarray(toks)[:g], np.asarray(lps)[:g]
        job.toks = np.where(in_range, toks, job.toks)
        job.lps = np.where(in_range, lps, job.lps)
        job.got |= in_range
        job.next_chunk += 1

        if job.next_chunk >= job.n_chunks:
            # admission complete: activate slots, emit first tokens
            for i, sid in enumerate(job.slots):
                slot = self._slots[sid]
                assert bool(job.got[i]), "first-token sample never captured"
                self.lengths[sid] = len(slot.prompt)
                self.last_token[sid] = int(job.toks[i])
                if slot.req._cancel.is_set():
                    # cancelled mid-admission: release without ever
                    # activating (the prefilled KV keys into the radix
                    # cache — a resubmit would reuse it)
                    slot = self._release_slot(sid, self._committed(sid))
                    slot.req.finish_reason = "cancelled"
                    self._complete(slot.req)
                    continue
                self.active[sid] = True
                if self._emit(slot.req, int(job.toks[i]),
                              float(job.lps[i])):
                    self._finish(sid)
            self._jobs.remove(job)
        if prof is not None:
            prof.mark("commit")

    # -- decode -------------------------------------------------------------

    def _preempt_youngest(self, protect: int) -> bool:
        """Free one live slot's pages (content-keyed into the radix
        cache — fully-written, valid KV) and requeue its request at the
        FRONT of the queue as a continuation. Victim selection: the
        YOUNGEST slot (max admit_seq) without QoS; with a TenantRegistry
        the order becomes (lowest priority class, most over fair share,
        youngest) — an interactive tenant's slots outlive a best-effort
        flood's. Returns False when no slot other than `protect` can be
        preempted."""
        candidates = [sid for sid, s in enumerate(self._slots)
                      if s is not None and self.active[sid]
                      and sid != protect]
        if not candidates:
            return False
        if self.qos is not None:
            sid = max(candidates,
                      key=lambda s: (*self.qos.victim_rank(
                          self._slots[s].req.tenant),
                          self._slots[s].admit_seq))
        else:
            sid = max(candidates, key=lambda s: self._slots[s].admit_seq)
        slot = self._release_slot(sid, self._committed(sid))
        self.preemptions += 1
        self.metrics.observe_requeue(slot.req, time.perf_counter())
        if self.qos is not None:
            self.qos.on_requeue(slot.req.tenant)
            # the flight-recorder iteration record tags preempt-requeues
            # with the victim tenant (post-mortem: WHO got evicted)
            self._iter_stats.setdefault("preempt_tenants", []).append(
                slot.req.tenant)
        with self._lock:
            self._pending.appendleft(slot.req)
        return True

    def _extend_chains(self, n_rounds: int) -> int:
        """On-demand policy: before a decode dispatch of n_rounds, grow
        every live slot's page chain to cover its worst-case window
        writes (lengths + n_rounds * window, clamped past stop_len where
        writes still span one final window).

        Pool exhaustion is handled in escalating order: take whatever
        pages ARE available (partial growth), preempt youngest-first,
        and — when nothing is preemptable (e.g. the other slots are
        still mid-admission) — BOUND this dispatch to the rounds every
        chain already covers instead of killing anyone: exhaustion is
        transient whenever admissions/queued work can free or activate
        slots by the next step. Returns the dispatchable round count
        (0 = skip this decode dispatch). A request is failed only when
        nothing can ever change: it is alone, the pool is fully drained
        into its chain, and it still cannot cover one round."""
        n_eff = n_rounds
        for sid in range(self.max_slots):
            slot = self._slots[sid]
            if slot is None or not self.active[sid]:
                continue
            while True:
                need_len = min(
                    int(self.lengths[sid]) + n_rounds * self.window,
                    slot.stop_len + self.window)
                delta = -(-need_len // self.page_size) - len(slot.pages)
                if delta <= 0:
                    break
                grab = min(delta, self.allocator.available)
                fresh = (self.allocator.alloc(grab,
                                              tenant=slot.req.tenant)
                         if grab > 0 else None)
                if fresh:
                    start = len(slot.pages)
                    slot.pages.extend(fresh)
                    self.tables[sid, start:len(slot.pages)] = fresh
                    if grab == delta:
                        break
                    continue  # partial fill; loop tries preemption next
                if self._preempt_youngest(protect=sid):
                    continue
                covered = len(slot.pages) * self.page_size
                r_ok = max(0, (covered - int(self.lengths[sid]))
                           // self.window)
                if (r_ok == 0 and not self._jobs
                        and self.num_pending == 0
                        and self.allocator.available == 0
                        and self.num_active == 1):
                    # genuinely impossible: alone with the whole pool.
                    # REQUEST-caused, like the admission-time twin —
                    # the router must not retry it on an identically-
                    # sized replica or charge the breaker for it
                    slot = self._release_slot(sid, self._committed(sid))
                    slot.req.finish_reason = (
                        "error: request needs more pages than the pool "
                        "can ever provide")
                    slot.req._request_fault = True
                    self._complete(slot.req)
                    break
                n_eff = min(n_eff, r_ok)
                break
        return n_eff

    def _chunk_rounds(self, active=None) -> int:
        """Rounds this dispatch: bounded by decode_chunk — SHRUNK to
        admit_decode_chunk while admission jobs are in flight, so a
        landing prompt is not stuck behind full decode bursts between
        its prefill chunks (this is the TTFT-vs-throughput knob; see
        __init__) — and by the tightest remaining budget (in rounds),
        rounded down to a power of two. `active` overrides the live
        mask (the overlap planner's PLANNED frame; its slightly stale
        remaining budgets can only overshoot, which the host emit loop
        already truncates — the mid-scan EOS case)."""
        if active is None:
            active = self.active
        rem = [s.req.max_new_tokens - len(s.req.tokens)
               for i, s in enumerate(self._slots)
               if s is not None and active[i]]
        if not rem:
            return 1
        chunk = self.decode_chunk
        if self._jobs and self.admit_decode_chunk is not None:
            chunk = self.admit_decode_chunk
        n = max(1, min(chunk, -(-min(rem) // self.window)))
        p = 1
        while p * 2 <= n:
            p *= 2
        return p

    def _gather_decode_rows(self, active=None):
        """COMPACTED decode sub-batch: one row per LIVE slot, padded to
        a power of two (compile cache). Rows carry sentinel slot ids /
        tables past the live count, so their writes drop everywhere
        (the cores' slot_ids indirection). Dispatching only live rows
        is what keeps decode cost proportional to occupancy — a batch
        half-full of mid-admission slots used to pay full max_slots
        gathers and matmuls every round.

        A fully-live batch skips the indirection (sl = None, rows ARE
        slots): steady state keeps the pre-compaction program, so the
        identity gathers of gstate / penalty rows are never paid there.

        Returns (live_ids, sl, arrays...) for the decode cores.
        `active` overrides the live mask (the overlap planner's
        planned frame; the gathered lengths/last rows are placeholders
        there — `_launch_plan` re-reads them from the committed ledger
        right before the launch)."""
        if active is None:
            active = self.active
        live_ids = np.flatnonzero(active)
        if len(live_ids) == self.max_slots:
            return (live_ids, None, active.copy(), self.lengths,
                    self.tables, self.last_token, self.stop_len,
                    self.samp_rows, self._gid, self._aid)
        bg = _pad_pow2(max(len(live_ids), 1))
        nl = len(live_ids)
        sl = np.full((bg,), self.max_slots, np.int32)
        sl[:nl] = live_ids
        slr = np.clip(sl, 0, self.max_slots - 1)
        live_g = np.zeros((bg,), bool)
        live_g[:nl] = True
        lengths = self.lengths[slr].copy()
        tables = self.tables[slr].copy()
        tables[nl:] = self.allocator.num_pages
        last = self.last_token[slr].copy()
        stop = self.stop_len[slr].copy()
        samp = _gather_samp_rows(self.samp_rows, slr, nl)
        gid = self._gid[slr].copy()
        gid[nl:] = 0
        aid = self._aid[slr].copy()
        aid[nl:] = 0
        return live_ids, sl, live_g, lengths, tables, last, stop, \
            samp, gid, aid

    def _spec_plan(self, live_ids):
        """Per-iteration speculation plan: (dispatch draft count,
        per-live-row draft caps). Fixed-length servers (no controller)
        plan (spec_drafts, None) — the pre-adaptive program, no
        draft_limit input at all. With the adaptive controller the
        dispatch width is QUANTIZED to {0, spec_drafts}: per-row caps
        already bound each slot's commits (and its drafted-token
        accounting) at its own length, and `n_drafts` is a static
        shape — one compiled program per distinct value — so
        intermediate widths would trade a sliver of verify compute for
        spec_drafts-many extra compiles. All-zero lengths plan
        (0, None): plain decode, no draft passes at all — the floor
        adaptive control promises on low-acceptance workloads."""
        if self.spec_drafts <= 0 or len(live_ids) == 0:
            return 0, None
        if self.spec_control is None:
            return self.spec_drafts, None
        lens = [self.spec_control.draft_len(int(s)) for s in live_ids]
        if max(lens) <= 0:
            return 0, None
        return self.spec_drafts, lens

    def _pad_limits(self, lens, n_rows: int):
        """(n_rows,) int32 per-row commit caps from the plan's per-live
        lengths (padding rows 0 — they never commit anyway)."""
        lim = np.zeros((n_rows,), np.int32)
        lim[:len(lens)] = lens
        return lim

    def _drafted_rows(self, g_iter: int, spec_lens, nl: int):
        """Per-live-row drafted-token counts for this dispatch's
        accounting (None = plain decode ran, nothing was drafted)."""
        if g_iter <= 0:
            return None
        return spec_lens if spec_lens is not None else [g_iter] * nl

    def _stage_spec_stats(self, g_iter: int, n_live: int,
                          st: dict | None = None) -> None:
        """Flight-recorder speculation fields for this iteration:
        draft rows funded, the dispatch draft count, and (adaptive)
        the current per-slot draft lengths. Token drafted/accepted
        fields land post-commit in `_commit_decode_rows`. `st`
        overrides the destination (a launch-ahead plan's staged
        stats)."""
        if self.spec_drafts <= 0:
            return
        if st is None:
            st = self._iter_stats
        st["spec_rows"] = n_live if g_iter > 0 else 0
        st["spec_window"] = g_iter + 1 if g_iter > 0 else 1
        if self.spec_control is not None:
            st["spec_draft_lens"] = self.spec_control.draft_lengths()

    def _decode_dispatch(self) -> None:
        if self._faults is not None:
            # injected dispatch failure (see _mixed_dispatch)
            self._faults.check("dispatch")
        prof = self._profiler
        n = self._chunk_rounds()
        if self.allocation == "ondemand":
            n_eff = self._extend_chains(n)
            if n_eff <= 0 or not self.active.any():
                return  # transient page famine — admissions continue,
                #         preemption candidates appear next step
            while n > n_eff:  # keep round counts powers of two (compile
                n //= 2      # cache) while honouring chain coverage
            n = max(1, n)
        if prof is not None:
            # round planning + chain extension/preemption policy
            prof.mark("admission")
        (live_ids, sl, live_g, lengths, tables, last_np, stop, samp_g,
         gid_np, aid_np) = self._gather_decode_rows()
        g_iter, spec_lens = self._spec_plan(live_ids)
        self._iter_stats.update(
            scheduler=self.scheduler, n_live=len(live_ids),
            decode_rounds=n,
            decode_tokens=len(live_ids) * (g_iter + 1) * n,
            decode_rows=int(live_g.shape[0]),
            compaction_ratio=len(live_ids) / max(int(live_g.shape[0]), 1))
        self._stage_spec_stats(g_iter, len(live_ids))
        if self.trace_recorder is not None:
            self._stage_decode_spans(live_ids, n)
        args = (jnp.asarray(lengths), jnp.asarray(tables),
                jnp.asarray(last_np), jnp.asarray(live_g))
        samp = jax.tree.map(jnp.asarray, samp_g)
        live = self.active
        use_rows = bool((self._needs_rows & live).any())
        use_bias = bool((self._has_bias & live).any())
        use_grammar = bool(((self._gid > 0) & live).any())
        gid = jnp.asarray(gid_np)
        # analysis: allow[lock-discipline] atomically-swapped reference,
        # rebuilt under _lock before any request using it is admitted
        grammar = self._grammar_dev if use_grammar else None
        use_lora = bool(((self._aid > 0) & live).any())
        lora = self.adapters.device_args() if use_lora else None
        aid = jnp.asarray(aid_np)
        sl_dev = None if sl is None else jnp.asarray(sl)
        if prof is not None:
            prof.mark("build")
        if g_iter > 0:
            lim_dev = (None if spec_lens is None else jnp.asarray(
                self._pad_limits(spec_lens, int(live_g.shape[0]))))
            self.state, lens, last, (toks, lps, counts) = _spec_rounds(
                self.params, self.state, *args,
                jnp.asarray(stop), self._next_rng(), samp,
                gid, grammar, lora, aid,
                self.draft_params, sl_dev, lim_dev,
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_rounds=n,
                n_drafts=g_iter, mesh=self.mesh,
                draft_cfg=self.draft_cfg, use_rows=use_rows,
                use_bias=use_bias)
            # analysis: allow[lock-discipline] THE sanctioned
            # per-iteration host sync under _step_lock (speculative arm)
            toks, lps, counts, lens, last = jax.device_get(
                (toks, lps, counts, lens, last))
        else:
            self.state, lens, last, (toks, lps, counts) = _decode_rounds(
                self.params, self.state, *args, self._next_rng(), samp,
                gid, grammar, lora, aid, sl_dev,
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_rounds=n,
                mesh=self.mesh, use_rows=use_rows, use_bias=use_bias)
            # analysis: allow[lock-discipline] THE sanctioned
            # per-iteration host sync under _step_lock (plain arm)
            toks, lps, counts, lens, last = jax.device_get(
                (toks, lps, counts, lens, last))
            toks, lps = toks[:, :, None], lps[:, :, None]
            if self.spec_drafts > 0 and self.spec_control is not None:
                # every live slot decoded plainly: draft-model caches
                # miss these positions (sticky off), n-gram slots
                # accrue probe credit
                self.spec_control.on_plain_dispatch(
                    [int(s) for s in live_ids], n)
        if prof is not None:
            prof.mark("device")
        self._commit_decode_rows(live_ids, toks, lps, counts, lens, last,
                                 self._drafted_rows(g_iter, spec_lens,
                                                    len(live_ids)))
        if prof is not None:
            prof.mark("commit")

    def _commit_decode_rows(self, live_ids, toks, lps, counts, lens,
                            last, drafted=None, owners=None) -> None:
        """Scatter a compacted decode dispatch's results back to slots
        and emit (shared by _decode_dispatch, _mixed_dispatch, and the
        async scheduler's _commit_inflight).

        `owners` (async scheduler only): the _Slot object each row was
        planned for. Between a launch-ahead and its commit a whole
        step ran — a row's slot may have been released and RE-OCCUPIED
        by a new admission, so the ledger writes and the emit loop
        must be identity-guarded per row, not just active-guarded.
        None (the sequential paths, where nothing can change between
        dispatch and commit) keeps the historical unconditional
        writes.

        `drafted` (per-live-row drafted-token counts, None when no
        draft rows ran) funds the speculation ledger from numbers the
        host already has: per committed round, a row drafted its own
        length and accepted `count - 1` of them. The adaptive
        controller is fed per round (its feedback signal), the
        per-tenant wasted-speculation counters once per dispatch —
        all plain host arithmetic on the synced counts, zero extra
        device work."""
        nl = len(live_ids)
        lens = np.asarray(lens)
        last = np.asarray(last)
        counts = np.asarray(counts)
        if owners is None:
            self.lengths[live_ids] = lens[:nl]
            self.last_token[live_ids] = last[:nl]
        else:
            for i in range(nl):
                sid = int(live_ids[i])
                if self._slots[sid] is owners[i] and self.active[sid]:
                    self.lengths[sid] = lens[i]
                    self.last_token[sid] = last[i]
        self.decode_rounds += int(counts.shape[0]) * nl
        self.decode_tokens_committed += int(counts.sum())
        sp_drafted = sp_accepted = 0
        spec_by_tenant: dict = {}
        for r in range(toks.shape[0]):
            for i, sid in enumerate(live_ids):
                slot = self._slots[sid]
                if slot is None or not self.active[sid] \
                        or (owners is not None and slot is not owners[i]):
                    continue
                c = int(counts[r, i])
                if drafted is not None and c > 0:
                    d = int(drafted[i])
                    a = min(max(c - 1, 0), d)
                    sp_drafted += d
                    sp_accepted += a
                    if self.spec_control is not None:
                        self.spec_control.observe(sid, d, a)
                    if self.qos is not None and d > 0:
                        dd, aa = spec_by_tenant.get(slot.req.tenant,
                                                    (0, 0))
                        spec_by_tenant[slot.req.tenant] = (dd + d, aa + a)
                for t in range(c):
                    if self._emit(slot.req, int(toks[r, i, t]),
                                  float(lps[r, i, t])):
                        self._finish(sid)
                        break
        if drafted is not None:
            self.spec_tokens_drafted += sp_drafted
            self.spec_tokens_accepted += sp_accepted
            st = self._iter_stats
            st["spec_tokens_drafted"] = (
                st.get("spec_tokens_drafted", 0) + sp_drafted)
            st["spec_tokens_accepted"] = (
                st.get("spec_tokens_accepted", 0) + sp_accepted)
            for tenant, (dd, aa) in spec_by_tenant.items():
                self.qos.charge_speculation(tenant, dd, aa)

    def _complete_admission_chunks(self, sel, ptoks, plps) -> None:
        """Prefill progress on the synced first-token candidates:
        capture samples in range, advance `done` (and the `planned`
        cursor when nothing is in flight to keep them ahead of it),
        and ACTIVATE completed admissions — the cancel-at-activation
        check included. THE one completion block, shared by
        `_mixed_dispatch` (sequential) and `_commit_inflight`
        (async), so the two paths can never drift."""
        ptoks, plps = np.asarray(ptoks), np.asarray(plps)
        for i, (job, take, d0) in enumerate(sel):
            sid = job.slots[0]
            rl = int(job.rem_lens[0])
            if d0 <= rl - 1 < d0 + take:
                job.toks[0] = ptoks[i]
                job.lps[0] = plps[i]
                job.got[0] = True
            job.done = d0 + take
            job.planned = max(job.planned, job.done)
            if job.done < rl:
                continue
            slot = self._slots[sid]
            assert bool(job.got[0]), \
                "first-token sample never captured"
            self.lengths[sid] = len(slot.prompt)
            self.last_token[sid] = int(job.toks[0])
            if slot.req._cancel.is_set():
                # cancelled mid-admission: release without ever
                # activating (the prefilled KV keys into the radix
                # cache — a resubmit would reuse it)
                slot = self._release_slot(sid, self._committed(sid))
                slot.req.finish_reason = "cancelled"
                self._complete(slot.req)
            else:
                self.active[sid] = True
                if self._emit(slot.req, int(job.toks[0]),
                              float(job.lps[0])):
                    self._finish(sid)
                elif getattr(slot.req, "_handoff", None) is not None:
                    # prefill complete with decode budget left: queue
                    # the disaggregation handoff callback; fired
                    # OUTSIDE _step_lock at the end of this step
                    self._handoff_ready.append(slot.req)
            self._jobs.remove(job)

    # -- mixed (stall-free) scheduling --------------------------------------

    def _mixed_rounds(self, n_live: int, prefill_demand: int,
                      win: int, active=None) -> int:
        """Decode rounds for a mixed iteration: the full steady-state
        count (`_chunk_rounds` WITHOUT the admit shrink — not stalling
        decode is the point), then squeezed to leave the budget at least
        one minimal prefill chunk when admissions are waiting, floored
        at one round and kept a power of two (compile cache). `win` is
        THIS iteration's decode window (current max draft length + 1 —
        adaptive speculation shrinks it with demand), so a slot's
        decode claim against the budget is its honest token count.
        `active` overrides the live mask (the overlap planner's
        planned frame — see _chunk_rounds)."""
        if active is None:
            active = self.active
        rem = [s.req.max_new_tokens - len(s.req.tokens)
               for i, s in enumerate(self._slots)
               if s is not None and active[i]]
        if not rem or not n_live:
            return 0
        n = max(1, min(self.decode_chunk, -(-min(rem) // win)))
        if prefill_demand > 0:
            fit = (self.mixed_token_budget - self._rem_buckets[0]) \
                // (n_live * win)
            n = min(n, max(fit, 1))
        p = 1
        while p * 2 <= n:
            p *= 2
        return p

    def _select_prefill(self, jobs, n_live: int, win: int,
                        n_rounds: int, planned: bool):
        """Token-budget prefill selection — THE shared policy half of
        a mixed iteration, used by `_mixed_dispatch` (cursor = the
        committed `done`) and `_plan_iteration` (cursor = the
        in-flight-inclusive `planned`), so the two paths can never
        drift (the array-staging half is `_build_prefill_group`).
        QoS virtual-time (or FIFO) order; decode rows are funded
        first, each selected job takes up to `prefill_chunk` tokens
        of the remainder, and when decode alone saturates the budget
        the OLDEST admission still gets one minimal chunk (TTFT stays
        bounded). Returns [(job, take, cursor_offset)]."""

        def cur(j):
            return j.planned if planned else j.done

        if self.qos is not None and jobs:
            order = self.qos.order_jobs(
                [self._slots[j.slots[0]].req.tenant for j in jobs])
            jobs = [jobs[i] for i in order]
        sel: list[tuple[_AdmitJob, int, int]] = []
        left = self.mixed_token_budget - n_live * win * n_rounds
        for job in jobs:
            if left <= 0:
                break
            rem_left = int(job.rem_lens[0]) - cur(job)
            take = min(rem_left, left, self.prefill_chunk)
            if take <= 0:
                continue
            sel.append((job, take, cur(job)))
            left -= take
        if jobs and not sel:
            job = jobs[0]
            take = min(int(job.rem_lens[0]) - cur(job),
                       self._rem_buckets[0])
            sel = [(job, take, cur(job))]
        return sel

    def _build_prefill_group(self, sel) -> dict:
        """Numpy staging for the ragged prefill half of one mixed
        iteration: one row per selected admission chunk, each at its
        own width, padded to a pow2 row count and a bucketed max width
        (compile cache). `sel` entries are (job, take, d0) — d0 is the
        remainder offset this chunk starts at: the committed cursor on
        the sequential path, the PLANNED cursor on the async path (so
        a launch-ahead iteration never re-prefills tokens already in
        flight). Shared verbatim by `_mixed_dispatch` and
        `_plan_iteration` so the two paths can never drift."""
        pad_tok = self.infer_cfg.pad_token_id
        b = self.max_slots
        g = len(sel)
        gp = _pad_pow2(max(g, 1))
        w = _bucket(max([t for _, t, _ in sel] + [1]),
                    self._mixed_buckets)
        chunk = np.full((gp, w), pad_tok, np.int32)
        widths = np.zeros((gp,), np.int32)
        g_lens = np.zeros((gp,), np.int32)
        g_tables = np.full((gp, self.max_pages_per_slot),
                           self.allocator.num_pages, np.int32)
        sample_at = np.zeros((gp,), np.int32)
        slot_ids = np.full((gp,), self.max_slots, np.int32)
        countm = np.zeros((gp,), bool)
        scatm = np.zeros((gp,), bool)
        scat_plens = []
        for i, (job, take, d0) in enumerate(sel):
            sid = job.slots[0]
            rl = int(job.rem_lens[0])
            chunk[i, :take] = job.rows[0, d0:d0 + take]
            widths[i] = take
            g_lens[i] = int(job.base_lens[0]) + d0
            g_tables[i] = self.tables[sid]
            sample_at[i] = min(max(rl - 1 - d0, 0), take - 1)
            slot_ids[i] = sid
            countm[i] = d0 <= rl - 1 < d0 + take
            scatm[i] = d0 == 0
            if d0 == 0:
                scat_plens.append(int(job.prompt_lens[0]))
        pb = (_bucket(max(scat_plens), self._admit_buckets)
              if scat_plens else self._admit_buckets[0])
        prompt_rows = np.full((gp, pb), pad_tok, np.int32)
        prompt_lens = np.zeros((gp,), np.int32)
        orig_lens = np.zeros((gp,), np.int32)
        for i, (job, take, d0) in enumerate(sel):
            sid = job.slots[0]
            pl = int(job.prompt_lens[0])
            prompt_lens[i] = pl
            orig_lens[i] = self.orig_len[sid]
            if d0 == 0:
                prompt_rows[i, :pl] = job.prompt_rows[0, :pl]
        sl_real = np.clip(slot_ids, 0, self.max_slots - 1)
        samp_g = _gather_samp_rows(self.samp_rows, sl_real, g)
        gid_g = self._gid[sl_real].copy()
        gid_g[g:] = 0
        gst0_g = self._gstate0[sl_real].copy()
        gst0_g[g:] = 0
        aid_g = self._aid[sl_real].copy()
        aid_g[g:] = 0
        sel_mask = np.zeros((b,), bool)
        sel_mask[[job.slots[0] for job, _, _ in sel]] = True
        return {"chunk": chunk, "widths": widths, "g_lens": g_lens,
                "g_tables": g_tables, "sample_at": sample_at,
                "slot_ids": slot_ids, "prompt_rows": prompt_rows,
                "prompt_lens": prompt_lens, "samp_g": samp_g,
                "orig_lens": orig_lens, "countm": countm,
                "scatm": scatm, "gid_g": gid_g, "gst0_g": gst0_g,
                "aid_g": aid_g, "sel_mask": sel_mask}

    def _handoff_prefetch(self, sel) -> None:
        """Overlapped KV export for the disaggregation handoff: for
        every selected admission that COMPLETES its prefill in the
        dispatch about to launch and carries a `handoff=` callback,
        gather the pages PRIOR chunks fully committed and start their
        device->host copies now — the transfer rides under the final
        chunk's compute, so the export at the handoff's commit point
        pays only the last chunk's pages (≤1 iteration of exposed
        latency). Must run BEFORE the dispatch statement: the dispatch
        donates `self.state`, so the pool buffers are invalid after
        the launch. Read-only — allocates nothing, releases nothing —
        so it is safe on the DD5 plan/launch path; the stash is
        validated (page-id prefix match) and consumed by
        `_export_request_locked`, or dropped at request completion."""
        ps = self.page_size
        for job, take, d0 in sel:
            if d0 + take < int(job.rem_lens[0]):
                continue  # not the final chunk
            sid = job.slots[0]
            slot = self._slots[sid]
            if slot is None or getattr(slot.req, "_handoff", None) is None:
                continue
            n_full = (int(job.base_lens[0]) + d0) // ps
            if n_full <= 0 or slot.req.request_id in self._handoff_stash:
                continue
            ids = np.asarray(slot.pages[:n_full])
            gathered = {name: pool[:, ids]
                        for name, pool in self.state["pools"].items()}
            draft = self.state.get("draft_pools")
            if draft is not None:
                for name, pool in draft.items():
                    gathered["draft/" + name] = pool[:, ids]
            for arr in gathered.values():
                # analysis: allow[dispatch-discipline] async D2H copy
                # START, not a host sync: nothing blocks here — the
                # copy overlaps the final prefill chunk and the
                # export's sanctioned device_get collects it
                arr.copy_to_host_async()
            self._handoff_stash[slot.req.request_id] = (
                tuple(slot.pages[:n_full]), gathered)

    def _mixed_dispatch(self) -> None:
        """One token-budget iteration: the multi-round decode dispatch
        for every live slot plus as many prefill-chunk tokens as fit
        under `mixed_token_budget`, fused into ONE jitted program with
        ONE host sync (`_mixed_step`).

        Budget split: decode rows are admitted first (live slots advance
        their full round count every iteration — the stall-free
        property); the remainder goes to in-flight admissions FIFO, each
        grabbing up to `prefill_chunk` tokens AT ITS OWN WIDTH — the
        ragged prefill group replaces the alternating scheduler's
        per-bucket admission dispatches. When decode alone saturates the
        budget, the OLDEST admission still gets one minimal chunk so
        TTFT stays bounded (the budget is a target, not a hard cap).
        Admitting slots not selected this iteration ride along inert:
        width 0 and sentinel tables, so nothing they own can be
        written."""
        if self._faults is not None:
            # injected dispatch failure: raises before any device work,
            # crashing this iteration the way a poisoned program would
            # (serve_forever catches, _fail_all unblocks every waiter,
            # the router's breaker/retry path takes it from there)
            self._faults.check("dispatch")
        b = self.max_slots
        demand = sum(int(j.rem_lens[0]) - j.done for j in self._jobs)
        n_live = int(self.active.sum())
        g0, _ = self._spec_plan(np.flatnonzero(self.active))
        n_rounds = self._mixed_rounds(n_live, demand, g0 + 1)
        if self.allocation == "ondemand" and n_rounds > 0:
            n_eff = self._extend_chains(n_rounds)
            if n_eff <= 0 or not self.active.any():
                n_rounds = 0  # transient page famine: prefill-only
            else:
                while n_rounds > n_eff:
                    n_rounds //= 2
                n_rounds = max(1, n_rounds)
        live = self.active if n_rounds > 0 else np.zeros((b,), bool)
        n_live = int(live.sum())
        # authoritative speculation plan for the dispatch (re-planned:
        # _extend_chains may have preempted a slot out of the live set);
        # draft rounds are funded as decode rows — a slot's claim is
        # `win` tokens per round, charged against prefill funding below
        g_iter, spec_lens = self._spec_plan(np.flatnonzero(self.active))
        win = g_iter + 1

        # weighted-fair funding of the iteration's prefill chunks
        # (QoS virtual-time order inside _select_prefill; called even
        # for a single job — it also advances the global virtual time,
        # so a tenant arriving after an idle gap resumes at the
        # current time instead of replaying idle credit)
        sel = self._select_prefill(self._jobs, n_live, win, n_rounds,
                                   planned=False)
        prof = self._profiler
        if prof is not None:
            # budget/round planning, chain extension, QoS funding
            # order, selection — the host deciding WHAT to dispatch
            prof.mark("admission")
        if not sel and not n_rounds:
            return
        if self.qos is not None:
            for job, take, _ in sel:
                self.qos.charge_prefill(
                    self._slots[job.slots[0]].req.tenant, take)
        self._iter_stats.update(
            scheduler="mixed", n_live=n_live, decode_rounds=n_rounds,
            decode_tokens=n_live * win * n_rounds,
            prefill_tokens=sum(t for _, t, _ in sel))
        if n_rounds > 0:
            self._stage_spec_stats(g_iter, n_live)
        if self.trace_recorder is not None:
            for job, take, d0 in sel:
                r = self._slots[job.slots[0]].req
                if r.trace is not None:
                    self._iter_spans.append(
                        (r, "prefill_chunk",
                         {"slot": job.slots[0], "tokens": take,
                          "offset": d0}))

        # -- ragged prefill group (one row per selected admission) ----------
        pf = self._build_prefill_group(sel)
        sel_mask = pf["sel_mask"]
        use_rows_p = bool((self._needs_rows & sel_mask).any())
        use_bias_p = bool((self._has_bias & sel_mask).any())

        # -- decode half (compacted: one row per live slot) -----------------
        (live_ids, sl_d, live_g, d_lens, d_tables, d_last, d_stop,
         samp_d, gid_d, aid_d) = self._gather_decode_rows()
        self._iter_stats.update(
            decode_rows=int(live_g.shape[0]) if n_rounds else 0,
            compaction_ratio=(n_live / max(int(live_g.shape[0]), 1)
                              if n_rounds else 1.0))
        if self.trace_recorder is not None and n_rounds > 0:
            self._stage_decode_spans(live_ids, n_rounds)
        if n_rounds == 0:
            live_g = np.zeros_like(live_g)
        use_rows_d = bool((self._needs_rows & live).any())
        use_bias_d = bool((self._has_bias & live).any())
        use_grammar = bool(((self._gid > 0) & (live | sel_mask)).any())
        use_lora = bool(((self._aid > 0) & (live | sel_mask)).any())

        if prof is not None:
            # host array prep done; the dispatch statement below (arg
            # transfer + launch) through the sanctioned device_get is
            # the device phase
            prof.mark("build")
        # disaggregation handoff: start the committed-page D2H copies
        # BEFORE the dispatch donates self.state (overlaps the final
        # prefill chunk)
        self._handoff_prefetch(sel)
        self.state, ptoks, plps, lens, last, (toks, lps, counts) = \
            _mixed_step(
                self.params, self.state, jnp.asarray(pf["chunk"]),
                jnp.asarray(pf["widths"]), jnp.asarray(pf["g_lens"]),
                jnp.asarray(pf["g_tables"]), jnp.asarray(pf["sample_at"]),
                jnp.asarray(pf["slot_ids"]), jnp.asarray(pf["prompt_rows"]),
                jnp.asarray(pf["prompt_lens"]),
                jax.tree.map(jnp.asarray, pf["samp_g"]),
                jnp.asarray(pf["orig_lens"]), jnp.asarray(pf["countm"]),
                jnp.asarray(pf["scatm"]), jnp.asarray(pf["gid_g"]),
                jnp.asarray(pf["gst0_g"]),
                jnp.asarray(d_lens), jnp.asarray(d_tables),
                jnp.asarray(d_last), jnp.asarray(live_g),
                jnp.asarray(d_stop),
                jax.tree.map(jnp.asarray, samp_d),
                jnp.asarray(gid_d),
                None if sl_d is None else jnp.asarray(sl_d),
                None if spec_lens is None else jnp.asarray(
                    self._pad_limits(spec_lens, int(live_g.shape[0]))),
                self._next_rng(),
                # analysis: allow[lock-discipline] atomically-swapped
                # reference, rebuilt under _lock pre-admission
                self._grammar_dev if use_grammar else None,
                self.adapters.device_args() if use_lora else None,
                jnp.asarray(pf["aid_g"]), jnp.asarray(aid_d),
                self.draft_params,
                cfg=self.cfg, infer_cfg=self.infer_cfg,
                n_rounds=n_rounds, n_drafts=g_iter,
                scatter_prompt=bool(pf["scatm"].any()), mesh=self.mesh,
                draft_cfg=self.draft_cfg,
                use_rows_p=use_rows_p, use_bias_p=use_bias_p,
                use_rows_d=use_rows_d, use_bias_d=use_bias_d)
        # analysis: allow[lock-discipline] THE sanctioned per-iteration
        # host sync — one fused dispatch, one device_get, under the
        # step lock that serializes the scheduler by design
        ptoks, plps, toks, lps, counts, lens, last = jax.device_get(
            (ptoks, plps, toks, lps, counts, lens, last))
        if prof is not None:
            prof.mark("device")

        if n_rounds > 0:
            if (g_iter == 0 and self.spec_drafts > 0
                    and self.spec_control is not None):
                self.spec_control.on_plain_dispatch(
                    [int(s) for s in live_ids], n_rounds)
            self._commit_decode_rows(live_ids, np.asarray(toks),
                                     np.asarray(lps), counts, lens, last,
                                     self._drafted_rows(g_iter, spec_lens,
                                                        len(live_ids)))

        # prefill progress: capture first tokens, activate completed
        # admissions (mirrors _run_one_chunk's completion block)
        self._complete_admission_chunks(sel, ptoks, plps)
        if prof is not None:
            prof.mark("commit")

    # -- async double-buffered scheduling (overlap on) ----------------------
    #
    # The pipelined loop (see the module docstring's overlap section):
    # each step plans iteration N+1 against the PLANNED frame while the
    # device runs iteration N, pays the one sanctioned device_get
    # commit, patches the plan's data-dependent inputs from the
    # just-committed ledger, and launches. Functions on this path obey
    # one extra invariant the dispatch-discipline pass checks
    # statically (DD5): the PLAN functions never release pages or tear
    # down slots — a page freed under an in-flight dispatch could be
    # re-allocated while the device still writes it.

    def _extend_chains_planned(self, n_rounds: int, planned_len,
                               planned_active) -> int:
        """Planned-frame chain growth for a launch-ahead dispatch:
        cover each planned-live slot's worst-case window writes using
        the PLANNED length upper bound (committed length + the
        in-flight dispatch's rounds*window). Unlike `_extend_chains`
        this NEVER preempts or fails a request (DD5 — no page releases
        while a dispatch is in flight): on famine it takes whatever
        pages are available and bounds the dispatch to the rounds
        every chain already covers. 0 drops the decode half; the
        pipeline then drains, and the next sequential iteration runs
        the full preemption escalation with nothing in flight."""
        n_eff = n_rounds
        for sid in range(self.max_slots):
            slot = self._slots[sid]
            if slot is None or not planned_active[sid]:
                continue
            need_len = min(int(planned_len[sid])
                           + n_rounds * self.window,
                           slot.stop_len + self.window)
            delta = -(-need_len // self.page_size) - len(slot.pages)
            if delta > 0:
                grab = min(delta, self.allocator.available)
                fresh = (self.allocator.alloc(grab,
                                              tenant=slot.req.tenant)
                         if grab > 0 else None)
                if fresh:
                    start = len(slot.pages)
                    slot.pages.extend(fresh)
                    self.tables[sid, start:len(slot.pages)] = fresh
            covered = len(slot.pages) * self.page_size
            r_ok = max(0, (covered - int(planned_len[sid]))
                       // self.window)
            n_eff = min(n_eff, r_ok)
        return n_eff

    def _plan_iteration(self) -> "_Plan | None":
        """Plan — and numpy-build — the NEXT dispatch against the
        PLANNED frame: the committed ledger plus the in-flight
        dispatch's deterministic effects (job cursors advanced by the
        takes it carries; slots it completes counted live; lengths at
        their rounds*window upper bound). This is the host policy work
        the overlap hides under the device: QoS/DRR funding order,
        token-budget split, chain growth, and all array staging happen
        here, so after the commit only a (rows,)-sized patch and the
        launch remain serialized.

        Returns None when there is nothing to dispatch (the pipeline
        drains). Never mutates the committed ledger beyond job.planned
        cursors, QoS prefill charges, and chain growth — and never
        releases pages (DD5).

        The injected-fault "dispatch" site is NOT checked here but in
        _step_overlap's steady-state path: checking per plan would
        hit the site twice on a pipeline-fill step (breaking the
        FaultPlan's one-hit-per-iteration pacing) and could fire
        AFTER the fill dispatch already streamed tokens — the fill
        prime's fault site is the NEXT step's check, matching the
        contiguous server's convention."""
        prof = self._profiler
        b = self.max_slots
        infl = self._inflight
        # --- the planned frame --------------------------------------------
        planned_active = self.active.copy()
        planned_len = self.lengths.copy()
        if infl is not None:
            if infl.n_rounds > 0:
                for i, sid_ in enumerate(infl.live_ids):
                    sid = int(sid_)
                    if planned_active[sid] \
                            and self._slots[sid] is infl.owners[i]:
                        planned_len[sid] = min(
                            int(planned_len[sid])
                            + infl.n_rounds * infl.win,
                            int(self.stop_len[sid]) + self.window)
            for sid in infl.activating:
                slot = self._slots[sid]
                if slot is not None:
                    planned_active[sid] = True
                    planned_len[sid] = len(slot.prompt)
        jobs = [j for j in self._jobs if j.planned < int(j.rem_lens[0])]
        if not jobs and not planned_active.any():
            return None
        stats: dict = {}
        spans: list = []
        if jobs:
            # --- token-budget mixed iteration (mirrors _mixed_dispatch)
            demand = sum(int(j.rem_lens[0]) - j.planned for j in jobs)
            n_live = int(planned_active.sum())
            # ONE speculation plan per planned iteration: unlike the
            # sequential path, _extend_chains_planned can never
            # preempt a slot out of the live set (DD5), so there is
            # nothing to re-plan after chain growth
            g_iter, spec_lens = self._spec_plan(
                np.flatnonzero(planned_active))
            n_rounds = self._mixed_rounds(n_live, demand, g_iter + 1,
                                          active=planned_active)
            if self.allocation == "ondemand" and n_rounds > 0:
                n_eff = self._extend_chains_planned(
                    n_rounds, planned_len, planned_active)
                if n_eff <= 0:
                    n_rounds = 0
                else:
                    while n_rounds > n_eff:
                        n_rounds //= 2
                    n_rounds = max(1, n_rounds)
            live = (planned_active if n_rounds > 0
                    else np.zeros((b,), bool))
            n_live = int(live.sum())
            win = g_iter + 1
            sel = self._select_prefill(jobs, n_live, win, n_rounds,
                                       planned=True)
            if not sel and not n_rounds:
                return None
            if self.qos is not None:
                for job, take, _ in sel:
                    self.qos.charge_prefill(
                        self._slots[job.slots[0]].req.tenant, take)
            activating: list[int] = []
            for job, take, d0 in sel:
                job.planned = d0 + take
                if job.planned >= int(job.rem_lens[0]):
                    activating.append(job.slots[0])
            stats.update(
                scheduler="mixed", n_live=n_live,
                decode_rounds=n_rounds,
                decode_tokens=n_live * win * n_rounds,
                prefill_tokens=sum(t for _, t, _ in sel))
            if n_rounds > 0:
                self._stage_spec_stats(g_iter, n_live, st=stats)
            if self.trace_recorder is not None:
                for job, take, d0 in sel:
                    r = self._slots[job.slots[0]].req
                    if r.trace is not None:
                        spans.append(
                            (r, "prefill_chunk",
                             {"slot": job.slots[0], "tokens": take,
                              "offset": d0}))
            if prof is not None:
                # planned-frame budget/round planning, chain growth,
                # QoS funding order, selection — overlapped host work
                prof.mark("admission")
            pf = self._build_prefill_group(sel)
            sel_mask = pf["sel_mask"]
            (live_ids, sl_d, live_g, d_lens, d_tables, d_last, d_stop,
             samp_d, gid_d, aid_d) = self._gather_decode_rows(live)
            stats.update(
                decode_rows=int(live_g.shape[0]) if n_rounds else 0,
                compaction_ratio=(n_live / max(int(live_g.shape[0]), 1)
                                  if n_rounds else 1.0))
            if self.trace_recorder is not None and n_rounds > 0:
                self._stage_decode_spans(live_ids, n_rounds, out=spans)
            if n_rounds == 0:
                live_g = np.zeros_like(live_g)
            plan = _Plan(
                kind="mixed", sel=sel, activating=activating,
                n_rounds=n_rounds, win=win, g_iter=g_iter,
                spec_lens=spec_lens, live_ids=live_ids, sl_d=sl_d,
                live_g=live_g, d_lens=d_lens, d_tables=d_tables,
                d_last=d_last, d_stop=d_stop, samp_d=samp_d,
                gid_d=gid_d, aid_d=aid_d,
                owners=[self._slots[int(s)] for s in live_ids],
                pf=pf, scatter_prompt=bool(pf["scatm"].any()),
                use_rows_p=bool((self._needs_rows & sel_mask).any()),
                use_bias_p=bool((self._has_bias & sel_mask).any()),
                use_rows_d=bool((self._needs_rows & live).any()),
                use_bias_d=bool((self._has_bias & live).any()),
                use_grammar=bool(
                    ((self._gid > 0) & (live | sel_mask)).any()),
                use_lora=bool(
                    ((self._aid > 0) & (live | sel_mask)).any()),
                stats=stats, spans=spans)
        else:
            # --- pure-decode iteration (mirrors _decode_dispatch) ---------
            n = self._chunk_rounds(active=planned_active)
            if self.allocation == "ondemand":
                n_eff = self._extend_chains_planned(
                    n, planned_len, planned_active)
                if n_eff <= 0:
                    return None
                while n > n_eff:
                    n //= 2
                n = max(1, n)
            if prof is not None:
                prof.mark("admission")
            (live_ids, sl_d, live_g, d_lens, d_tables, d_last, d_stop,
             samp_d, gid_d, aid_d) = self._gather_decode_rows(
                 planned_active)
            g_iter, spec_lens = self._spec_plan(live_ids)
            stats.update(
                scheduler=self.scheduler, n_live=len(live_ids),
                decode_rounds=n,
                decode_tokens=len(live_ids) * (g_iter + 1) * n,
                decode_rows=int(live_g.shape[0]),
                compaction_ratio=(len(live_ids)
                                  / max(int(live_g.shape[0]), 1)))
            self._stage_spec_stats(g_iter, len(live_ids), st=stats)
            if self.trace_recorder is not None:
                self._stage_decode_spans(live_ids, n, out=spans)
            plan = _Plan(
                kind="decode", sel=[], activating=[], n_rounds=n,
                win=g_iter + 1, g_iter=g_iter, spec_lens=spec_lens,
                live_ids=live_ids, sl_d=sl_d, live_g=live_g,
                d_lens=d_lens, d_tables=d_tables, d_last=d_last,
                d_stop=d_stop, samp_d=samp_d, gid_d=gid_d, aid_d=aid_d,
                owners=[self._slots[int(s)] for s in live_ids],
                pf=None, scatter_prompt=False,
                use_rows_p=False, use_bias_p=False,
                use_rows_d=bool(
                    (self._needs_rows & planned_active).any()),
                use_bias_d=bool(
                    (self._has_bias & planned_active).any()),
                use_grammar=bool(
                    ((self._gid > 0) & planned_active).any()),
                use_lora=bool(((self._aid > 0) & planned_active).any()),
                stats=stats, spans=spans)
        # stage the launch-stable inputs onto the device NOW, inside
        # the overlap window: jnp.asarray is an async host->device
        # feed (DD2 deliberately never flags those), so these
        # transfers ride behind the in-flight program and the
        # serialized launch tail pays only the (rows,)-sized patched
        # arrays. jnp.asarray on an already-device array is a no-op,
        # so _launch_plan's conversion sites serve both paths.
        if plan.pf is not None:
            pf = plan.pf
            for k in ("chunk", "widths", "g_lens", "g_tables",
                      "sample_at", "slot_ids", "prompt_rows",
                      "prompt_lens", "orig_lens", "countm", "scatm",
                      "gid_g", "gst0_g", "aid_g"):
                pf[k] = jnp.asarray(pf[k])
            pf["samp_g"] = jax.tree.map(jnp.asarray, pf["samp_g"])
        plan.d_stop = jnp.asarray(plan.d_stop)
        plan.samp_d = jax.tree.map(jnp.asarray, plan.samp_d)
        plan.gid_d = jnp.asarray(plan.gid_d)
        plan.aid_d = jnp.asarray(plan.aid_d)
        if prof is not None:
            prof.mark("build")
        return plan

    def _launch_plan(self, plan: "_Plan") -> None:
        """Patch the plan's data-dependent decode inputs from the
        just-committed ledger, then launch it ASYNCHRONOUSLY — no
        device_get here; the sync is the next step's
        `_commit_inflight`. The patch is the whole serialized cost of
        re-anchoring the plan: a (rows,) re-gather of lengths / last
        tokens / table rows plus deadening rows whose slot died at the
        commit (their sentinel tables drop every device write, and
        `owners` masks their host commit)."""
        prof = self._profiler
        live_ids = plan.live_ids
        nl = len(live_ids)
        if nl and plan.n_rounds > 0:
            if plan.sl_d is None:
                # rows ARE slots: the ledger views are the patched
                # arrays (dead slots already carry sentinel tables and
                # active=False from _release_slot)
                plan.live_g = self.active.copy()
                plan.d_lens = self.lengths
                plan.d_tables = self.tables
                plan.d_last = self.last_token
            else:
                for i in range(nl):
                    sid = int(live_ids[i])
                    alive = (self._slots[sid] is plan.owners[i]
                             and self.active[sid])
                    plan.live_g[i] = alive
                    plan.d_lens[i] = self.lengths[sid]
                    plan.d_last[i] = self.last_token[sid]
                    plan.d_tables[i] = self.tables[sid]
            if plan.kind == "decode" and not plan.live_g[:nl].any():
                # every planned row died at the commit: nothing left
                # to dispatch — drain the pipeline instead of paying a
                # fully-inert program
                return
        # analysis: allow[lock-discipline] atomically-swapped
        # reference, rebuilt under _lock pre-admission
        grammar = self._grammar_dev if plan.use_grammar else None
        lora = self.adapters.device_args() if plan.use_lora else None
        sl_dev = None if plan.sl_d is None else jnp.asarray(plan.sl_d)
        lim_dev = (None if plan.spec_lens is None else jnp.asarray(
            self._pad_limits(plan.spec_lens, int(plan.live_g.shape[0]))))
        if plan.kind == "mixed":
            pf = plan.pf
            # disaggregation handoff: the in-flight dispatch committed
            # before this launch, so the plan's sel cursors equal the
            # committed ones — start the D2H copies for admissions the
            # plan completes, before the dispatch donates self.state
            self._handoff_prefetch(plan.sel)
            self.state, ptoks, plps, lens, last, (toks, lps, counts) = \
                _mixed_step(
                    self.params, self.state, jnp.asarray(pf["chunk"]),
                    jnp.asarray(pf["widths"]),
                    jnp.asarray(pf["g_lens"]),
                    jnp.asarray(pf["g_tables"]),
                    jnp.asarray(pf["sample_at"]),
                    jnp.asarray(pf["slot_ids"]),
                    jnp.asarray(pf["prompt_rows"]),
                    jnp.asarray(pf["prompt_lens"]),
                    jax.tree.map(jnp.asarray, pf["samp_g"]),
                    jnp.asarray(pf["orig_lens"]),
                    jnp.asarray(pf["countm"]),
                    jnp.asarray(pf["scatm"]), jnp.asarray(pf["gid_g"]),
                    jnp.asarray(pf["gst0_g"]),
                    jnp.asarray(plan.d_lens),
                    jnp.asarray(plan.d_tables),
                    jnp.asarray(plan.d_last), jnp.asarray(plan.live_g),
                    jnp.asarray(plan.d_stop),
                    jax.tree.map(jnp.asarray, plan.samp_d),
                    jnp.asarray(plan.gid_d), sl_dev, lim_dev,
                    self._next_rng(), grammar, lora,
                    jnp.asarray(pf["aid_g"]), jnp.asarray(plan.aid_d),
                    self.draft_params,
                    cfg=self.cfg, infer_cfg=self.infer_cfg,
                    n_rounds=plan.n_rounds, n_drafts=plan.g_iter,
                    scatter_prompt=plan.scatter_prompt, mesh=self.mesh,
                    draft_cfg=self.draft_cfg,
                    use_rows_p=plan.use_rows_p,
                    use_bias_p=plan.use_bias_p,
                    use_rows_d=plan.use_rows_d,
                    use_bias_d=plan.use_bias_d)
            futures = (ptoks, plps, toks, lps, counts, lens, last)
        else:
            args = (jnp.asarray(plan.d_lens),
                    jnp.asarray(plan.d_tables),
                    jnp.asarray(plan.d_last), jnp.asarray(plan.live_g))
            samp = jax.tree.map(jnp.asarray, plan.samp_d)
            gid = jnp.asarray(plan.gid_d)
            aid = jnp.asarray(plan.aid_d)
            if plan.g_iter > 0:
                self.state, lens, last, (toks, lps, counts) = \
                    _spec_rounds(
                        self.params, self.state, *args,
                        jnp.asarray(plan.d_stop), self._next_rng(),
                        samp, gid, grammar, lora, aid,
                        self.draft_params, sl_dev, lim_dev,
                        cfg=self.cfg, infer_cfg=self.infer_cfg,
                        n_rounds=plan.n_rounds, n_drafts=plan.g_iter,
                        mesh=self.mesh, draft_cfg=self.draft_cfg,
                        use_rows=plan.use_rows_d,
                        use_bias=plan.use_bias_d)
            else:
                self.state, lens, last, (toks, lps, counts) = \
                    _decode_rounds(
                        self.params, self.state, *args,
                        self._next_rng(), samp, gid, grammar, lora,
                        aid, sl_dev,
                        cfg=self.cfg, infer_cfg=self.infer_cfg,
                        n_rounds=plan.n_rounds, mesh=self.mesh,
                        use_rows=plan.use_rows_d,
                        use_bias=plan.use_bias_d)
            futures = (toks, lps, counts, lens, last)
        t = (prof.mark("launch") if prof is not None
             else time.perf_counter())
        self._iter_launch_ts = t
        self._inflight = _Inflight(
            kind=plan.kind, futures=futures, sel=plan.sel,
            activating=plan.activating, live_ids=live_ids,
            owners=plan.owners, n_rounds=plan.n_rounds, win=plan.win,
            g_iter=plan.g_iter, spec_lens=plan.spec_lens,
            stats=plan.stats, spans=plan.spans, t_launch=t)

    def _commit_inflight(self) -> None:
        """Sync and commit the in-flight dispatch: THE serialized
        critical path of the async scheduler. One device_get brings
        the sampled tokens home; the ledger writes, token emits,
        activations, speculation feedback, and deferred sweep reaps
        all run on the synced values — guarded per row by the owners
        identity captured at plan time (a whole step ran since the
        launch)."""
        infl, self._inflight = self._inflight, None
        t_wait = time.perf_counter()
        # analysis: allow[lock-discipline] THE sanctioned per-iteration
        # host sync — one launched dispatch, one device_get, under the
        # step lock that serializes the scheduler by design
        vals = jax.device_get(infl.futures)
        prof = self._profiler
        if prof is not None:
            prof.mark("device")
        st = infl.stats
        st["overlap"] = True
        st["inflight_depth"] = 1
        # how long the device ran ahead of the host needing results:
        # launch -> the moment this step's overlapped work finished
        # and the sync began. Residual device phase > 0 means the
        # device was still busy through the whole overlap window.
        st["overlap_launch_lead_ms"] = (t_wait - infl.t_launch) * 1e3
        # install BEFORE the commit work below: _commit_decode_rows
        # appends its spec-token fields to self._iter_stats, and they
        # belong to THIS record
        self._iter_stats = st
        self._iter_spans = infl.spans
        n_rounds, g_iter = infl.n_rounds, infl.g_iter
        if infl.kind == "mixed":
            ptoks, plps, toks, lps, counts, lens, last = vals
        else:
            toks, lps, counts, lens, last = vals
            if g_iter == 0:
                toks = np.asarray(toks)[:, :, None]
                lps = np.asarray(lps)[:, :, None]
        if n_rounds > 0:
            if (g_iter == 0 and self.spec_drafts > 0
                    and self.spec_control is not None):
                self.spec_control.on_plain_dispatch(
                    [int(s) for s in infl.live_ids], n_rounds)
            self._commit_decode_rows(
                infl.live_ids, np.asarray(toks), np.asarray(lps),
                counts, lens, last,
                self._drafted_rows(g_iter, infl.spec_lens,
                                   len(infl.live_ids)),
                owners=infl.owners)
        if infl.kind == "mixed":
            self._complete_admission_chunks(infl.sel, ptoks, plps)
        self._apply_reaps()
        if prof is not None:
            prof.mark("commit")

    def _overlap_sweep(self) -> None:
        """Sweep for an overlapped step: cancelled / deadline-expired
        SLOT holders are only MARKED (active=False + queued on
        _reaped) — the in-flight dispatch is still writing their
        pages, and releasing mid-flight could hand a page to a new
        admission while the device writes it. `_apply_reaps` releases
        them right after the commit, in this same step. Pending-queue
        expiry is pure host state and runs exactly like the
        sequential sweep."""
        job_slots = {s for job in self._jobs for s in job.slots}
        marked = {sid for sid, _, _ in self._reaped}
        now = None
        for sid, slot in enumerate(self._slots):
            if slot is None or sid in job_slots or sid in marked:
                continue
            if slot.req._cancel.is_set():
                self.active[sid] = False
                self._reaped.append((sid, slot, "cancelled"))
                continue
            if slot.req.deadline is not None:
                if now is None:
                    now = time.perf_counter()
                if now > slot.req.deadline:
                    self.active[sid] = False
                    self._reaped.append((sid, slot, "deadline"))
        self._expire_pending(now)

    def _apply_reaps(self) -> None:
        """Deferred-release half of `_overlap_sweep`, run just after
        the commit: the marked slots' pages are fully committed KV
        now, so they release through the normal content-keyed path
        (reusable in the prefix cache) and the requests complete."""
        if not self._reaped:
            return
        reaped, self._reaped = self._reaped, []
        for sid, slot, reason in reaped:
            if self._slots[sid] is not slot:
                continue  # already torn down (failure path)
            s = self._release_slot(sid, self._committed(sid))
            s.req.finish_reason = reason
            self._complete(s.req)

    def _drain_handoff_ready(self) -> None:
        """Fire the queued disaggregation handoff callbacks — OUTSIDE
        `_step_lock`, on the scheduler thread, right after the step
        that activated them: the callback (the ReplicatedRouter's
        hook) enqueues a `migrate_export`, which needs the step lock
        this thread just released. Each request's callback fires at
        most once; a request that finished or cancelled between
        activation and here is skipped. Callback exceptions are the
        router's problem, never the scheduler's — the request keeps
        decoding locally either way (the handoff is an optimization,
        not a correctness event)."""
        # analysis: allow[lock-discipline] scheduler-thread-only list:
        # appended inside the step (under _step_lock) and drained here
        # on the SAME thread right after the lock releases — no second
        # accessor exists, the guard inference is a false positive
        if not self._handoff_ready:
            return
        # analysis: allow[lock-discipline] same scheduler-thread-only
        # swap as above
        ready, self._handoff_ready = self._handoff_ready, []
        for req in ready:
            h = req._handoff
            req._handoff = None  # at most once
            if (h is None or req._done.is_set()
                    or req._cancel.is_set()):
                continue
            try:
                h(req)
            except Exception:  # noqa: BLE001 — router-side failure
                pass

    def _step_overlap(self) -> int:
        """One pipelined scheduler iteration (overlap on). With a
        dispatch in flight: plan iteration N+1 (sweep marks, QoS/DRR
        admission, the whole numpy build) WHILE the device runs
        iteration N, then sync+commit N, patch, and launch N+1 — one
        fused dispatch and one device_get per step, with only the
        commit/patch/launch tail serialized against the device.
        With nothing in flight (cold start, post-drain, famine): run
        the byte-identical sequential iteration, then PRIME the
        pipeline by planning and launching the next dispatch before
        returning. Handoff callbacks queued by the step fire after
        the lock releases (`_drain_handoff_ready`)."""
        with self._step_lock:
            self.tracer.step_start()
            prof = self._profiler
            try:
                if self._faults is not None:
                    self._faults.maybe_stall()
                    self._faults.maybe_wedge(self._stop)
                if prof is not None:
                    prof.begin()
                al = self.allocator
                al.telemetry.iteration = self.flight.iterations + 1
                c0 = (al.pages_allocated, al.pages_released,
                      al.evictions)
                if self._inflight is None:
                    # pipeline fill: the sequential iteration, plus a
                    # launch-ahead prime so the NEXT step overlaps
                    self._sweep_cancelled()
                    if prof is not None:
                        prof.mark("sweep")
                    self._start_admissions()
                    if prof is not None:
                        prof.mark("admission")
                    self._iter_stats = {}
                    p0 = self.preemptions
                    t0 = (prof.t0 if prof is not None
                          else time.perf_counter())
                    if self._jobs:
                        self._mixed_dispatch()
                    elif self.active.any():
                        self._decode_dispatch()
                    if self._jobs or self.active.any():
                        plan = self._plan_iteration()
                        if plan is not None:
                            self._launch_plan(plan)
                    self._record_iteration(t0, p0, c0)
                    if self._iter_stats:
                        self.last_busy_ts = self._iter_stats["ts"]
                    else:
                        self.idle_iterations += 1
                    ret = self.num_active
                else:
                    # steady state: one commit + one launch per step
                    self._overlap_sweep()
                    if prof is not None:
                        prof.mark("sweep")
                    self._start_admissions()
                    if prof is not None:
                        prof.mark("admission")
                    p0 = self.preemptions
                    t0 = (prof.t0 if prof is not None
                          else time.perf_counter())
                    if self._faults is not None:
                        # injected dispatch failure: ONE hit per step
                        # (the fill path's site lives inside its
                        # sequential dispatch), raised before the
                        # commit below — serve_forever catches,
                        # _fail_all drops the in-flight futures and
                        # unblocks every waiter
                        self._faults.check("dispatch")
                    plan = self._plan_iteration()
                    self._commit_inflight()
                    if plan is not None:
                        self._launch_plan(plan)
                    self._record_iteration(t0, p0, c0)
                    self.last_busy_ts = self._iter_stats["ts"]
                    ret = self.num_active
            finally:
                self.tracer.step_end()
        self._drain_handoff_ready()
        return ret

    # -- scheduler ----------------------------------------------------------

    def _sweep_cancelled(self) -> None:
        """Reap cancelled and deadline-expired requests that already
        hold a slot (pages go back through the normal `_release_slot`
        path — the KV they wrote is fully committed, so it stays
        reusable in the prefix cache). Slots still inside an admission
        job are left to finish their (bounded, already-batched)
        chunks — _run_one_chunk checks the cancel flag at activation,
        and an expired request is reaped by the next sweep. Expired
        PENDING requests are reaped here too, so a deadline is honored
        even if the request never reaches a slot. The expiry clock is
        read lazily: zero reads per iteration when no live request
        carries a deadline."""
        job_slots = {s for job in self._jobs for s in job.slots}
        now = None
        for sid, slot in enumerate(self._slots):
            if slot is None or sid in job_slots:
                continue
            if slot.req._cancel.is_set():
                slot = self._release_slot(sid, self._committed(sid))
                slot.req.finish_reason = "cancelled"
                self._complete(slot.req)
                continue
            if slot.req.deadline is not None:
                if now is None:
                    now = time.perf_counter()
                if now > slot.req.deadline:
                    slot = self._release_slot(sid, self._committed(sid))
                    slot.req.finish_reason = "deadline"
                    self._complete(slot.req)
        self._expire_pending(now)

    def _expire_pending(self, now: float | None) -> None:
        """Reap deadline-expired PENDING requests (pure host-queue
        state — safe whether or not a dispatch is in flight, so both
        the sequential and the overlap sweep share it). The expiry
        clock stays lazy: zero reads when nothing pending carries a
        deadline."""
        with self._lock:
            expired = []
            if any(r.deadline is not None for r in self._pending):
                if now is None:
                    now = time.perf_counter()
                keep = collections.deque()
                for r in self._pending:
                    if r.deadline is not None and now > r.deadline:
                        expired.append(r)
                    else:
                        keep.append(r)
                self._pending = keep
            for r in expired:
                if self.qos is not None:
                    self.qos.on_pending_removed(r.tenant)
        for r in expired:
            r.finish_reason = "deadline"
            self._complete(r)

    def step(self) -> int:
        """One scheduler iteration: reap cancellations, start
        admissions, then dispatch. With the mixed scheduler and any
        admission in flight, prefill chunks and decode rows fuse into
        ONE token-budget dispatch (stall-free); otherwise (steady state,
        or the alternating scheduler) prefill chunks and a multi-round
        decode dispatch run separately. Thread-safe.

        With the iteration profiler enabled (the default) every phase
        boundary is stamped (`sweep` / `admission` here; `build` /
        `device` / `commit` inside the dispatch paths; `epilogue` in
        _record_iteration) and the iteration's t0 is the profiler's —
        so a busy flight record's `duration_ms` covers the WHOLE
        iteration and equals `host_ms + device_wait_ms` exactly.
        Disabled, the historical two-read clock (dispatch start →
        epilogue) is byte-identical.

        With the async double-buffered scheduler enabled (overlap on,
        mixed scheduler — the default) the iteration is PIPELINED:
        see `_step_overlap`. overlap=False keeps the sequential body
        below byte-identical to the pre-overlap build."""
        if self._overlap_enabled:
            return self._step_overlap()
        ret = self._step_sequential()
        self._drain_handoff_ready()
        return ret

    def _step_sequential(self) -> int:
        """The sequential iteration body of step() (overlap off or the
        alternating scheduler), split out so step() can fire handoff
        callbacks AFTER `_step_lock` releases. Byte-identical work."""
        with self._step_lock:
            self.tracer.step_start()
            prof = self._profiler
            try:
                if self._faults is not None:
                    # injected host stall (the scheduler thread pays
                    # it like a slow host/device round) and the wedge
                    # site: block holding _step_lock until stop() —
                    # the scenario _fail_all's bounded acquire covers
                    self._faults.maybe_stall()
                    self._faults.maybe_wedge(self._stop)
                if prof is not None:
                    prof.begin()
                al = self.allocator
                # page-flow baseline for this iteration's flight record
                # (sweep + admission allocate/release too, so capture
                # before both) + the telemetry recency stamp: the
                # flight index THIS iteration will get if it is busy
                al.telemetry.iteration = self.flight.iterations + 1
                c0 = (al.pages_allocated, al.pages_released,
                      al.evictions)
                self._sweep_cancelled()
                if prof is not None:
                    prof.mark("sweep")
                self._start_admissions()
                if prof is not None:
                    prof.mark("admission")
                self._iter_stats = {}
                p0 = self.preemptions
                t0 = prof.t0 if prof is not None else time.perf_counter()
                if self._mixed_enabled and self._jobs:
                    self._mixed_dispatch()
                else:
                    for job in list(self._jobs):
                        self._run_one_chunk(job)
                    if self.active.any():
                        self._decode_dispatch()
                self._record_iteration(t0, p0, c0)
                if self._iter_stats:
                    self.last_busy_ts = self._iter_stats["ts"]
                else:
                    self.idle_iterations += 1
                return self.num_active
            finally:
                self.tracer.step_end()

    def _stage_decode_spans(self, live_ids, n_rounds: int,
                            out: list | None = None) -> None:
        """Stage one decode_segment span per traced live slot for this
        iteration's decode dispatch (stamped with the shared iteration
        frame by _record_iteration). `out` overrides the destination
        (a launch-ahead plan's staged spans)."""
        if out is None:
            out = self._iter_spans
        for sid in live_ids:
            s = self._slots[int(sid)]
            if s is not None and s.req.trace is not None:
                out.append(
                    (s.req, "decode_segment",
                     {"slot": int(sid), "rounds": n_rounds}))

    def _record_iteration(self, t0: float, p0: int,
                          c0: tuple[int, int, int]) -> None:
        """Flight-recorder epilogue for one busy scheduler iteration:
        the dispatch paths filled `_iter_stats` with their token split;
        this adds the budget/occupancy derived fields and appends ONE
        ring-buffer record. Idle iterations (nothing dispatched) leave
        `_iter_stats` empty and record nothing, so the ring holds the
        last N *busy* iterations.

        Tracing epilogue too: spans the dispatch paths staged this
        iteration are stamped with the SAME (t0, now) frame and the
        flight-recorder iteration index — the cross-link that lets a
        slow span answer "what else was the scheduler doing that
        iteration" in one hop, at the cost of zero extra clock reads
        beyond the duration_ms one the recorder already pays."""
        spans, self._iter_spans = self._iter_spans, []
        st = self._iter_stats
        if not st:
            return
        decode_tokens = st.get("decode_tokens", 0)
        st["tokens_scheduled"] = decode_tokens + st.get("prefill_tokens", 0)
        if st.get("scheduler") == "mixed":
            st["budget_tokens"] = self.mixed_token_budget
            st["budget_utilization"] = (st["tokens_scheduled"]
                                        / self.mixed_token_budget)
        # every preemption requeues its request at the queue front, so
        # this single field IS both the preemption and the requeue count
        st["preemptions"] = self.preemptions - p0
        if self.qos is not None:
            # per-tenant fair-share gauge (generated share over
            # weighted entitlement, 1.0 = fair) — the post-mortem view
            # of WHO the iteration's tokens went to
            st["tenant_fair_share"] = {
                k: round(v, 4)
                for k, v in self.qos.fair_shares().items()}
        st["n_jobs"] = len(self._jobs)
        st["pending"] = self.num_pending
        # KV-pool telemetry (joins phases_ms in the record): the
        # iteration's page flow (deltas against the step-start
        # baseline — sweep/admission included) and the occupancy split
        # at record time. Plain int reads/len()s on state this thread
        # owns; the evictable-fraction histogram is the HBM-pressure
        # watermark /metrics carries.
        al = self.allocator
        st["pages_allocated"] = al.pages_allocated - c0[0]
        st["pages_released"] = al.pages_released - c0[1]
        st["pages_evicted"] = al.evictions - c0[2]
        free, cached = len(al._free), len(al._evictable)
        st["pool_free"] = free
        st["pool_cached"] = cached
        st["pool_active"] = al.num_pages - free - cached
        frac = (free + cached) / max(al.num_pages, 1)
        st["pool_evictable_frac"] = frac
        h = self._cache_hists.get("evictable_frac")
        if h is not None:
            h.observe(frac)
        # live-migration flow (deltas accrued since the last busy
        # record): requests resumed here / evacuated from here — only
        # present on records that saw one, so unmigrated records stay
        # byte-identical
        mig_in, mig_out = self._migration.drain_flight_deltas()
        if mig_in or mig_out:
            st["migrated_in"] = mig_in
            st["migrated_out"] = mig_out
        prof = self._profiler
        if prof is not None:
            # everything since the commit mark (the stats assembly
            # above, fair-share scans included) is epilogue; the mark
            # doubles as the iteration's closing clock read
            now = prof.mark("epilogue")
            phases = prof.phases_ms()
            st["t_start"] = t0
            st["phases_ms"] = phases
            st["duration_ms"] = (now - t0) * 1e3
            overlapped = bool(st.get("overlap"))
            st.update(derive_gap_fields(phases, st["duration_ms"],
                                        overlapped))
            hists = self._phase_hists
            if overlapped:
                # sweep/admission/build ran under the in-flight
                # device program: fold them into the `overlap` series
                # so the histogram-derived host-gap stays honest (the
                # fine split survives in this flight record)
                hists["overlap"].observe(
                    sum(phases.get(p, 0.0) for p in OVERLAP_PHASES))
                for p, v in phases.items():
                    if p not in OVERLAP_PHASES:
                        hists[p].observe(v)
            else:
                for p, v in phases.items():
                    hists[p].observe(v)
        else:
            now = time.perf_counter()
            st["duration_ms"] = (now - t0) * 1e3
        if self._iter_launch_ts is not None:
            # the launch-ahead performed THIS step (the Perfetto
            # inflight track pairs it with the NEXT record's residual
            # device wait)
            st["t_launch"] = self._iter_launch_ts
            self._iter_launch_ts = None
        if self._brownout is not None:
            # overload grading over signals this record already owns;
            # the pending head's age is the queue-growth signal (one
            # deque peek under the state lock)
            with self._lock:
                head = self._pending[0] if self._pending else None
                age = (0.0 if head is None or head.submit_time is None
                       else now - head.submit_time)
            st["brownout_level"] = self._brownout.observe(
                pending_age_s=age,
                budget_utilization=st.get("budget_utilization", 0.0),
                host_gap_frac=st.get("host_gap_frac", 0.0))
        if self._anomaly is not None:
            # watchdog feed: every signal is a field this record
            # already owns (the epilogue clock mark, int deltas) —
            # zero extra dispatches/syncs/clock reads
            hb = self._anomaly_cache_base
            cur = (al.prefix_hit_pages, al.prefix_miss_pages)
            self._anomaly_cache_base = cur
            hit_d = cur[0] - hb[0]
            fired = self._anomaly.observe_iteration(
                now=now, host_gap_frac=st.get("host_gap_frac", 0.0),
                pending=st["pending"],
                preempt_delta=st["preemptions"],
                cache_lookup_delta=hit_d + (cur[1] - hb[1]),
                cache_hit_delta=hit_d,
                overload_level=st.get("brownout_level", 0))
            if fired:
                self._on_anomaly(fired)
        st["ts"] = time.time()
        self.flight.record(**st)
        if spans:
            idx = self.flight.iterations
            for req, name, tags in spans:
                req.trace.add_span(name, t0, now, iteration=idx,
                                   **tags)

    # -- observability ------------------------------------------------------

    def _collect_metrics(self) -> None:
        """Scrape-path mirror of host scheduler + allocator state into
        the registry (never touched on the serving hot path)."""
        reg = self.metrics.registry
        reg.gauge("active_slots",
                  "Requests currently decoding").set(self.num_active)
        reg.gauge("pending_requests",
                  "Queued requests awaiting admission").set(
                      self.num_pending)
        reg.gauge("admission_jobs",
                  "Chunked-prefill admission jobs in flight").set(
                      # analysis: allow[lock-discipline] scrape-path
                      # len() of a GIL-atomic list; a gauge may lag
                      # the iteration that is mutating it
                      len(self._jobs))
        reg.counter("tokens_emitted_total",
                    "Lifetime generated tokens").set_total(
                        self.tokens_emitted)
        reg.counter("decode_rounds_total",
                    "Lifetime decode dispatch rounds").set_total(
                        self.decode_rounds)
        reg.counter("decode_tokens_committed_total",
                    "Lifetime tokens committed by decode rounds"
                    ).set_total(self.decode_tokens_committed)
        reg.counter("preemptions_total",
                    "Lifetime on-demand-paging preemptions").set_total(
                        self.preemptions)
        # idle-vs-dead disambiguation: an idle scheduler keeps
        # incrementing the counter while the gauge ages; a dead one
        # freezes both
        reg.counter("idle_iterations_total",
                    "step() calls that dispatched nothing").set_total(
                        self.idle_iterations)
        reg.gauge("last_busy_ts",
                  "Unix time of the last busy iteration (0 until the "
                  "first)").set(self.last_busy_ts)
        reg.counter("spec_tokens_drafted_total",
                    "Draft tokens proposed on committing rows' behalf"
                    ).set_total(self.spec_tokens_drafted)
        reg.counter("spec_tokens_accepted_total",
                    "Draft tokens accepted and committed"
                    ).set_total(self.spec_tokens_accepted)
        rate = (self.spec_control.accept_rate()
                if self.spec_control is not None else
                self.spec_tokens_accepted
                / max(self.spec_tokens_drafted, 1))
        reg.gauge("spec_accept_rate",
                  "Rolling speculative accept rate (accepted/drafted "
                  "per committed round; lifetime ratio without the "
                  "adaptive controller)").set(rate)
        # failure-domain observability (inference/faults.py): the
        # families register unconditionally (zeros when nothing is
        # configured) so the docs drift check — and dashboards — see
        # them before the first incident, which is the whole point
        reg.counter("unserialized_teardown_total",
                    "_fail_all teardowns that proceeded after the "
                    "bounded _step_lock acquire timed out (slot state "
                    "torn down against a wedged scheduler)").set_total(
                        self.unserialized_teardowns)
        from cloud_server_tpu.inference.faults import SITES
        from cloud_server_tpu.inference.qos import PRIORITY_CLASSES
        fstats = (self._faults.stats() if self._faults is not None
                  else None)
        for site in SITES:
            reg.counter("faults_injected_total",
                        "Deliberately injected faults that fired, "
                        "per site (inference/faults.py; zero without "
                        "an armed FaultPlan)",
                        labels={"site": site}).set_total(
                            0 if fstats is None
                            else fstats["fired"][site])
        bstats = (self._brownout.stats() if self._brownout is not None
                  else None)
        reg.gauge("brownout_level",
                  "Current overload brownout level (0 healthy, "
                  "1 shedding best_effort, 2 shedding batch too)").set(
                      0 if bstats is None else bstats["level"])
        for cls in PRIORITY_CLASSES:
            reg.counter("brownout_shed_total",
                        "Admissions refused by overload brownout, per "
                        "priority class (429 with jittered "
                        "Retry-After)",
                        labels={"class": cls}).set_total(
                            0 if bstats is None
                            else bstats["shed_total"].get(cls, 0))
        # live-migration counters (inference/migration.py): same
        # unconditional-registration rule as the fault families —
        # export and import halves each count one operation
        mstats = self._migration.stats()
        reg.counter("migrations_started_total",
                    "Live-migration operations started (request "
                    "exports + imports; inference/migration.py)"
                    ).set_total(mstats["started"])
        reg.counter("migrations_completed_total",
                    "Live-migration operations completed (the "
                    "request left this replica with its state, or "
                    "resumed here at the exact next token)"
                    ).set_total(mstats["completed"])
        reg.counter("migrations_failed_total",
                    "Live-migration operations that failed — the "
                    "request fell back to fail-fast "
                    "(`retriable: false`) or to the normal drain "
                    "wait").set_total(mstats["failed"])
        stats = self.allocator.stats()
        reg.gauge("pages_total",
                  "KV page pool size").set(stats.pages_total)
        reg.gauge("pages_free",
                  "Unallocated KV pages").set(stats.pages_free)
        reg.gauge("pages_cached",
                  "Refcount-0 prefix-cached KV pages (evictable)").set(
                      stats.pages_cached)
        reg.gauge("pages_active",
                  "KV pages referenced by live slots").set(
                      stats.pages_active)
        reg.counter("prefix_hit_pages_total",
                    "Admission pages served from the radix prefix cache"
                    ).set_total(stats.prefix_hit_pages)
        reg.counter("prefix_miss_pages_total",
                    "Admission pages that missed the radix prefix cache"
                    ).set_total(stats.prefix_miss_pages)
        reg.counter("prefix_evictions_total",
                    "Prefix-cache pages evicted under memory pressure"
                    ).set_total(stats.evictions)
        reg.counter("prefix_hit_tokens_total",
                    "Token value of prefix-cache page hits (prefill "
                    "work the cache absorbed)").set_total(
                        stats.hits_tokens)
        reg.counter("pages_allocated_total",
                    "Fresh KV pages handed out by the allocator"
                    ).set_total(self.allocator.pages_allocated)
        reg.counter("pages_released_total",
                    "KV pages whose refcount reached zero (cached or "
                    "freed)").set_total(self.allocator.pages_released)
        reg.gauge("cache_namespaces",
                  "Distinct KV namespaces (base model + LoRA "
                  "adapters) that touched the prefix cache").set(
                      stats.namespaces)
        if self.qos is not None:
            # per-tenant cache attribution mirrors, following the QoS
            # cardinality rule: labeled series exist only when a
            # TenantRegistry bounds the tenant set (the ledger's keys
            # are names the registry already resolved). Eager over the
            # registry's configured tenants — the families exist (and
            # the docs drift check sees them) before any traffic.
            tstats = self.allocator.telemetry.tenant_stats()
            for name in set(self.qos.tenants()) | set(tstats):
                led = tstats.get(name, {})
                lbl = {"tenant": name}
                reg.counter(
                    "tenant_prefix_hit_tokens_total",
                    "Prompt tokens served from prefix-cache hits at "
                    "lookup, per tenant", labels=lbl).set_total(
                        led.get("hit_tokens", 0))
                reg.counter(
                    "tenant_prefix_miss_tokens_total",
                    "Prompt tokens the cache could not serve "
                    "(freshly prefilled, tail included), per tenant",
                    labels=lbl).set_total(
                        led.get("miss_tokens", 0))
                reg.counter(
                    "tenant_prefix_evicted_tokens_total",
                    "Token value of the tenant's cached chains "
                    "evicted under memory pressure", labels=lbl
                    ).set_total(
                        led.get("evicted_pages", 0) * self.page_size)
                reg.counter(
                    "tenant_prefix_saved_tokens_total",
                    "Prefill tokens the tenant actually skipped at "
                    "admission (realized savings; diverges from hit "
                    "tokens exactly when page-famine retries wasted "
                    "lookups)", labels=lbl).set_total(
                        led.get("saved_tokens", 0))
                reg.gauge(
                    "tenant_cache_pages_held",
                    "KV pages currently referenced by the tenant's "
                    "slots (shared pages count once per holder)",
                    labels=lbl).set(led.get("pages_held", 0))
            self.qos.mirror_metrics(reg)
        if self.slo is not None:
            self.slo.mirror_metrics(reg)
        # anomaly watchdog + tail retention: families registered
        # unconditionally (zeros) so the /metrics catalog is stable —
        # the faults_injected_total pattern
        from cloud_server_tpu.inference.anomaly import RULES
        astats = (self._anomaly.stats(events=0)
                  if self._anomaly is not None else None)
        for rule in RULES:
            reg.gauge("anomaly_active",
                      "1 while the watchdog rule's anomaly window is "
                      "open (inference/anomaly.py; zero without an "
                      "anomaly config)",
                      labels={"rule": rule}).set(
                          0.0 if astats is None
                          else float(rule in astats["active"]))
            reg.counter("anomalies_total",
                        "Watchdog rule activations (one per anomaly "
                        "window opened, per rule)",
                        labels={"rule": rule}).set_total(
                            0 if astats is None
                            else astats["fired_total"][rule])
        rec = self.trace_recorder
        tstats = (rec.tail_stats() if rec is not None
                  and rec.tail_capacity > 0 else None)
        reg.counter("trace_tail_retained_total",
                    "Head-unsampled finished requests whose span "
                    "trees the tail-retention predicate kept"
                    ).set_total(0 if tstats is None else
                                sum(tstats["retained_total"].values()))
        reg.counter("trace_tail_evicted_total",
                    "Tail-retained trees evicted from the bounded "
                    "tail ring").set_total(
                        0 if tstats is None
                        else tstats["evicted_total"])
        reg.counter("anomaly_bundles_total",
                    "Forensic debug bundles auto-captured on anomaly "
                    "activation (bundle_on_anomaly)").set_total(
                        self._bundles_captured)

    def metrics_snapshot(self) -> dict:
        """Mergeable snapshot of every registered metric (the /metrics
        and /stats source; ReplicatedRouter merges these across
        replicas)."""
        return self.metrics.registry.snapshot()

    def iteration_profile_stats(self) -> dict | None:
        """The /stats `iteration_profile` summary: per-phase
        count/mean/p50/p99 ms + the aggregate host-gap fraction,
        computed from the per-phase histograms (so behind the router
        the same helper over the fleet-merged snapshot reports true
        fleet percentiles). None with profiling disabled."""
        from cloud_server_tpu.inference.iteration_profile import (
            profile_summary)
        return profile_summary(self.metrics_snapshot())

    def speculation_stats(self) -> dict:
        """The /stats `speculation` summary. Counts are fleet-mergeable
        (ReplicatedRouter sums them and recomputes `accept_rate` from
        the merged totals, like `tenant_fair_share`); `draft_lens` is
        this server's live per-slot view and is dropped by the fleet
        merge."""
        out = {
            "enabled": self.spec_drafts > 0,
            "source": ("off" if self.spec_drafts <= 0 else
                       "draft_model" if self.draft_cfg is not None
                       else "ngram"),
            "max_drafts": self.spec_drafts,
            "adaptive": self.spec_control is not None,
            "tokens_drafted": self.spec_tokens_drafted,
            "tokens_accepted": self.spec_tokens_accepted,
            "accept_rate": (self.spec_tokens_accepted
                            / max(self.spec_tokens_drafted, 1)),
        }
        if self.spec_control is not None:
            out["rolling_accept_rate"] = self.spec_control.accept_rate()
            out["draft_lens"] = {
                str(k): v
                for k, v in self.spec_control.draft_lengths().items()}
        return out

    def cache_stats(self) -> dict:
        """The /stats `cache` block and GET /debug/cache source: pool
        occupancy, lifetime prefix hit/miss/eviction counts with the
        hit rate, the per-tenant attribution table, the hot-prefix
        top-K sketch, and the eviction forensics (recent ring +
        victim×forcer matrix). Counts are fleet-mergeable —
        `ReplicatedRouter.cache_stats()` sums them and recomputes
        `hit_rate` / `evictable_frac` from the merged totals via
        `cache_telemetry.merge_cache_stats` (the `tenant_fair_share`
        rule: ratios never add). Scrape-path only; same lock-free
        monitoring reads as `prefix_cache_stats` (see its audit
        note)."""
        from cloud_server_tpu.inference.cache_telemetry import hit_rate
        s = self.allocator.stats()
        tel = self.allocator.telemetry
        tstats = tel.tenant_stats()
        # full-page-granular hit/miss (the ledger counts every
        # un-shared full prompt page as a miss, where the allocator's
        # walk counter records one break per walk) — so `hit_rate`
        # here is the true page hit rate, the number item 3's
        # prefix-aware routing scores against
        hit_pages = sum(led["hit_pages"] for led in tstats.values())
        miss_pages = sum(led["miss_pages"] for led in tstats.values())
        return {
            "pool": {
                "pages_total": s.pages_total,
                "pages_free": s.pages_free,
                "pages_cached": s.pages_cached,
                "pages_active": s.pages_active,
                "evictable_frac": ((s.pages_free + s.pages_cached)
                                   / max(s.pages_total, 1)),
            },
            "prefix": {
                "hit_pages": hit_pages,
                "miss_pages": miss_pages,
                "hit_tokens": s.hits_tokens,
                "evictions": s.evictions,
                "hit_rate": hit_rate(hit_pages, miss_pages),
            },
            "namespaces": s.namespaces,
            # the SAME snapshot the hit/miss aggregate above came
            # from — a second tenant_stats() could observe newer walks
            # and ship a payload whose tenants table contradicts its
            # own prefix block
            "tenants": tstats,
            "top_prefixes": tel.top_prefixes(),
            "recent_evictions": tel.recent_evictions(64),
            "eviction_matrix": tel.eviction_matrix(),
        }

    def overlap_stats(self) -> dict:
        """The /stats `overlap` block: the async scheduler's resolved
        knob state and the live pipeline depth. Scrape path only."""
        return {
            "enabled": self.overlap,
            "active": self._overlap_enabled,
            # analysis: allow[lock-discipline] racy-by-design
            # monitoring read; staleness bounded by one iteration
            "inflight_depth": 0 if self._inflight is None else 1,
        }

    def brownout_stats(self) -> dict | None:
        """The /stats `brownout` block (level, signal EWMAs vs
        thresholds, per-class shed counts); None with brownout
        disabled. Scrape path only."""
        return None if self._brownout is None else self._brownout.stats()

    def fault_stats(self) -> dict | None:
        """Per-site injected-fault hit/fired counts (the /stats
        `faults` block); None with no FaultPlan. Scrape path only."""
        return None if self._faults is None else self._faults.stats()

    def migration_stats(self) -> dict:
        """Live-migration counters (the /stats `migration` block):
        export/import starts, completions, failures, tokens salvaged,
        KV pages moved. Counts are fleet-mergeable —
        `ReplicatedRouter.migration_stats()` sums them and recomputes
        the success rate from the merged totals. Scrape path only."""
        return self._migration.stats()

    @property
    def ready(self) -> bool:
        """Readiness (vs the liveness /healthz always reported): False
        while draining or stopped, so load balancers — and the
        ReplicatedRouter's placement — stop routing new work here
        while in-flight requests finish."""
        # analysis: allow[lock-discipline] benign racy read: a stale
        # verdict delays placement by one pick; taking _lock here would
        # put a contended acquire on every router _pick
        return not self._draining and not self._stop.is_set()

    def lookup_trace(self, request_id: str) -> dict | None:
        """Span tree for one sampled request id (live or retained),
        else None (unsampled, evicted, or tracing disabled)."""
        rec = self.trace_recorder
        return None if rec is None else rec.lookup(request_id)

    def trace_trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the sampled ring + live requests (the
        /traces export source)."""
        rec = self.trace_recorder
        return [] if rec is None else rec.trees(n)

    def slo_report(self) -> dict | None:
        """Per-class SLO attainment + burn rates (the /slo source;
        ReplicatedRouter merges these across replicas). None when no
        SLO config is set."""
        return None if self.slo is None else self.slo.report()

    def flight_window(self, n: int | None = None) -> list[dict]:
        """The last `n` (default: all retained) per-iteration flight
        recorder records, oldest first."""
        return self.flight.window(n)

    def request_trace(self, n_steps: int,
                      logdir: str | os.PathLike) -> None:
        """Arm the /debug/trace capture: the next `n_steps` scheduler
        iterations run inside utils.tracing.capture_trace(logdir)."""
        self.tracer.request(n_steps, logdir)

    def anomaly_stats(self) -> dict | None:
        """The /stats `anomaly` block (active windows, per-rule
        activation counts, the bounded event ring); None with no
        watchdog. Scrape path only."""
        return None if self._anomaly is None else self._anomaly.stats()

    def anomaly_events(self, n: int | None = None) -> list[dict]:
        """Watchdog event dicts for the Perfetto marker track; empty
        with no watchdog."""
        return ([] if self._anomaly is None
                else self._anomaly.events(n))

    def tail_trace_trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the tail-retained ring (anomalous requests
        kept past head sampling); empty with tail retention off."""
        rec = self.trace_recorder
        return ([] if rec is None or rec.tail_capacity <= 0
                else rec.tail_trees(n))

    def tail_trace_stats(self) -> dict | None:
        """The /stats tail-retention block; None with tail retention
        off."""
        rec = self.trace_recorder
        return (None if rec is None or rec.tail_capacity <= 0
                else rec.tail_stats())

    def _on_anomaly(self, fired) -> None:
        """Activation-edge reactions (rare by construction): snapshot
        a forensic bundle into the bounded ring when
        `bundle_on_anomaly` is set, and arm the existing /debug/trace
        capture machinery when the watchdog config asks for one.
        Forensics must never take the scheduler down — arming races
        (a capture already running) and bundle failures are
        swallowed."""
        if self._bundle_on_anomaly:
            try:
                self._bundles.append(self.debug_bundle(
                    trigger="anomaly:" + ",".join(fired)))
                self._bundles_captured += 1
            except Exception:  # noqa: BLE001 — see docstring
                pass
        wd = self._anomaly
        if wd is not None and wd.capture_iters > 0 and wd.capture_dir:
            try:
                self.tracer.request(wd.capture_iters, wd.capture_dir)
            except ValueError:
                pass  # a capture is already armed/running

    def debug_bundle(self, n: int = 64, *,
                     trigger: str = "manual") -> dict:
        """One-shot forensic artifact (the GET /debug/bundle payload):
        everything an incident post-mortem would otherwise stitch
        from six endpoints — metrics, the scheduler flight window,
        retained + tail span trees, cache/brownout/migration state,
        SLO report, fault/anomaly state — as one JSON-ready dict.
        `n` bounds the ring exports (flight records and trace trees).
        Scrape path only (auto-capture calls it once per activation
        edge, which is rare by the watchdog's hysteresis)."""
        return {
            "schema": "cloud_server.debug_bundle/v1",
            "trigger": trigger,
            "ts": time.time(),
            "anomaly": self.anomaly_stats(),
            "metrics": self.metrics_snapshot(),
            "profile": self.iteration_profile_stats(),
            "flight": self.flight_window(n),
            "traces": self.trace_trees(n),
            "tail_traces": self.tail_trace_trees(n),
            "tail_retention": self.tail_trace_stats(),
            "slo": self.slo_report(),
            "cache": self.cache_stats(),
            "brownout": self.brownout_stats(),
            "migration": self.migration_stats(),
            "faults": self.fault_stats(),
            "overlap": self.overlap_stats(),
        }

    def debug_bundles(self, n: int | None = None) -> list[dict]:
        """The bounded ring of auto-captured bundles (oldest first;
        `n` bounds from the newest end, n <= 0 means none)."""
        if n is not None and n <= 0:
            return []
        bundles = list(self._bundles)
        return bundles if n is None else bundles[-n:]

    def run_until_idle(self) -> None:
        # analysis: allow[lock-discipline] idle-polling bool() of a
        # GIL-atomic list; step() below observes the exact state
        while self.num_pending or self.num_active or self._jobs:
            self.step()

    # -- live migration -----------------------------------------------------

    def migrate_export(self, req: Request, *, reason: str = "failover",
                       evacuate: bool = True):
        """Snapshot one live (slot or pending) request for migration
        to another replica (inference/migration.py).

        Runs at the scheduler's sanctioned commit point: under
        `_step_lock`, with any in-flight dispatch committed first, so
        the host token stream, the KV watermark, and the grammar
        position are exact. The chain's committed full pages ride
        along via the export's one sanctioned `device_get` (off the
        plan path, so DD5 holds — see analysis/dispatch.py's
        sanctioned-sync inventory).

        With `evacuate=True` (default) the request leaves this server
        atomically with the snapshot: its slot releases through the
        normal content-keyed path (the committed KV stays reusable in
        the local prefix cache) and NOBODY completes the handle — the
        caller re-admits the snapshot elsewhere and mirrors the
        outcome back. A request mid-admission (chunked prefill still
        dispatching) is not exportable and raises RuntimeError; the
        caller lets it finish or fail normally."""
        led = self._migration
        led.record_export_start()
        try:
            if self._faults is not None:
                self._faults.check("migrate_export")
            with self._step_lock:
                if self._inflight is not None:
                    # drain the pipeline first: the in-flight
                    # dispatch's tokens belong to the stream being
                    # exported
                    self._commit_inflight()
                snap, sid, committed = self._export_request_locked(
                    req, reason)
                if evacuate:
                    self._evacuate_request_locked(req, sid, committed)
        except BaseException:
            led.record_export_failed()
            raise
        led.record_export_done(len(snap.tokens), snap.n_kv_pages())
        return snap

    def migrate_salvage(self, req: Request, *,
                        reason: str = "failover"):
        """Crash-path export: a host-only snapshot (no KV — a failed
        scheduler's `_fail_all` already released its pages unkeyed)
        built from the Request handle alone. Token exactness does not
        depend on the pages: the destination re-prefills
        prompt + tokens and resumes at the exact next token; the KV
        transfer is only ever a prefill-cost optimization."""
        led = self._migration
        led.record_export_start()
        try:
            if self._faults is not None:
                self._faults.check("migrate_export")
            snap = self._build_snapshot(req, reason, (), None)
        except BaseException:
            led.record_export_failed()
            raise
        led.record_export_done(len(snap.tokens), 0)
        return snap

    def _build_snapshot(self, req: Request, reason: str,
                        chain_tokens, kv: dict | None):
        from cloud_server_tpu.inference.migration import (
            MIGRATION_VERSION, MigrationSnapshot)
        now = time.perf_counter()
        tr = req.trace
        return MigrationSnapshot(
            version=MIGRATION_VERSION, request_id=req.request_id,
            reason=reason, prompt=tuple(req.prompt),
            tokens=tuple(req.tokens), logprobs=tuple(req.logprobs),
            emit_times=tuple(req.emit_times), seed_used=req.seed_used,
            sampling=req.sampling, adapter=req.adapter,
            tenant=req.tenant, slo_class=req.slo_class,
            max_new_tokens=req.max_new_tokens,
            # the REMAINDER, not the absolute stamp: deadlines are
            # per-host monotonic clocks and must not cross machines
            deadline_remaining_s=(None if req.deadline is None
                                  else req.deadline - now),
            trace_ctx=(None if tr is None
                       else (tr.trace_id, tr.root_span_id, True)),
            chain_tokens=tuple(chain_tokens), kv_pages=kv)

    def _export_request_locked(self, req: Request, reason: str):
        """Locate `req` (slot or pending) and snapshot it. Caller
        holds `_step_lock` with no dispatch in flight. Returns
        (snapshot, slot_id | None, committed_tokens)."""
        sid = next((i for i, s in enumerate(self._slots)
                    if s is not None and s.req is req), None)
        if sid is None:
            with self._lock:
                if req not in self._pending:
                    raise RuntimeError(
                        "request is not live on this server (already "
                        "finished, failed, or cancelled)")
            return self._build_snapshot(req, reason, (), None), None, []
        if any(sid in job.slots for job in self._jobs):
            raise RuntimeError(
                "request is mid-admission (chunked prefill in "
                "flight); not exportable until prefill completes")
        committed = self._committed(sid)
        ps = self.page_size
        n_full = len(committed) // ps
        kv = None
        stash = self._handoff_stash.pop(req.request_id, None)
        if n_full:
            slot = self._slots[sid]
            page_ids = list(slot.pages[:n_full])
            # handoff prefetch (see _handoff_prefetch): pages gathered
            # before the final prefill chunk's dispatch, host copies
            # already overlapped under its compute. Valid only while
            # they are still a PREFIX of the slot's chain (a
            # preemption/re-admission in between re-keys the pages —
            # the stash is then stale and the full gather below pays
            # the whole transfer, a missed optimization, never a
            # correctness event).
            pre: dict = {}
            n_pre = 0
            if stash is not None:
                sids_, gathers = stash
                if list(sids_) == page_ids[:len(sids_)]:
                    pre, n_pre = gathers, len(sids_)
            gathered: dict = {}
            if n_pre < n_full:
                rem = np.asarray(page_ids[n_pre:])
                for name, pool in self.state["pools"].items():
                    gathered[name] = pool[:, rem]
                draft = self.state.get("draft_pools")
                if draft is not None:
                    for name, pool in draft.items():
                        gathered["draft/" + name] = pool[:, rem]
            # analysis: allow[lock-discipline] the migration export's
            # ONE sanctioned host sync — at the commit point, off the
            # plan path (DD5), under the step lock that serializes
            # the scheduler by design (analysis/dispatch.py
            # SANCTIONED_SYNCS). The prefetched half completes
            # instantly (its D2H copy already ran under the final
            # prefill chunk); only the remainder pays transfer here.
            pre_h, rem_h = jax.device_get((pre, gathered))
            if not rem_h:
                kv = pre_h
            elif not pre_h:
                kv = rem_h
            else:
                kv = {name: np.concatenate((pre_h[name], rem_h[name]),
                                           axis=1)
                      for name in rem_h}
        return (self._build_snapshot(req, reason,
                                     committed[:n_full * ps], kv),
                sid, committed)

    def _evacuate_request_locked(self, req: Request, sid: int | None,
                                 committed: list) -> None:
        """Remove the exported request from this server WITHOUT
        completing it — the caller now owns the handle's fate. The
        slot (if any) releases content-keyed, so its committed KV
        stays reusable in the local prefix cache. The source half of
        the trace closes here; the destination joins the same tree
        via the snapshot's trace context."""
        if sid is not None:
            if (self._slots[sid] is None
                    or self._slots[sid].req is not req):
                raise RuntimeError("slot changed under export")
            self._release_slot(sid, committed)
        else:
            with self._lock:
                try:
                    self._pending.remove(req)
                except ValueError:
                    raise RuntimeError(
                        "request left the pending queue during "
                        "export") from None
                if self.qos is not None:
                    self.qos.on_pending_removed(req.tenant)
        # a `finish:` event so the SOURCE half of the trace closes as
        # a complete, gap-free tree (build_tree keys the root's end on
        # the final finish event; the destination's continuation tree
        # carries the rest of the request under the same trace id)
        req.record_event("finish:migrated", time.perf_counter())
        if self.trace_recorder is not None and (
                req.trace is not None or req.tail_trace is not None):
            if req.trace is None:
                # deterministic tail retention: the SOURCE half of a
                # migrated tree always retains (mirrors the
                # destination's migrate_of/handoff_of tag), so a
                # router-merged tree is never half-missing
                req.tail_trace.annotate(migrated_out=True)
            self.trace_recorder.finish(req)

    def migrate_import(self, snap, *, stream=None, fail_handler=None,
                       trace_ctx: tuple | None = None,
                       deadline_s: float | None = None) -> Request:
        """Re-admit a migrated request on THIS server. The snapshot's
        KV pages are keyed into the pool under their radix chain keys
        (shared prefixes dedupe on arrival — BlockAllocator.
        import_chain) and scattered back with a device_put + one
        dispatch, no host sync (DD2 holds). The request then enters
        through the NORMAL continuation admission: its admission
        prompt is prompt + generated tokens, so the prefix walk
        re-hits the imported pages and decode resumes at the exact
        next token. A failed or partial KV import degrades to plain
        re-prefill — a cache miss, never a correctness event.

        Returns the new Request handle. Only NEW tokens are emitted
        on `stream`; the snapshot's already-delivered tokens are
        pre-filled so the client keeps one contiguous stream."""
        from cloud_server_tpu.inference.migration import (
            MIGRATION_VERSION)
        led = self._migration
        led.record_import_start()
        try:
            if self._faults is not None:
                self._faults.check("migrate_import")
            if snap.version != MIGRATION_VERSION:
                raise ValueError(
                    f"migration snapshot version {snap.version} != "
                    f"{MIGRATION_VERSION}")
            if snap.remaining_new_tokens() <= 0:
                raise ValueError(
                    "snapshot has no decode budget left to resume")
            if snap.kv_pages:
                try:
                    self._import_pages(snap)
                except Exception:
                    pass  # re-prefill instead; exactness unaffected
            if deadline_s is None:
                deadline_s = snap.deadline_remaining_s
            req = self.submit(
                list(snap.prompt),
                max_new_tokens=snap.max_new_tokens, stream=stream,
                sampling=snap.sampling, adapter=snap.adapter,
                tenant=snap.tenant,
                trace_ctx=(snap.trace_ctx if trace_ctx is None
                           else trace_ctx),
                deadline_s=deadline_s, fail_handler=fail_handler,
                _migration=snap)
        except BaseException:
            led.record_import_failed()
            raise
        led.record_import_done()
        return req

    def _import_pages(self, snap) -> int:
        """Scatter the snapshot's KV pages into the pool under their
        chain keys. Holds `_step_lock` so the keyed-but-not-yet-
        written window is invisible: admissions (the only readers)
        run inside the step, which serializes behind this scatter.
        Returns the number of pages installed (0 = full dedupe or a
        skipped transfer)."""
        tenant = (self.qos.resolve(snap.tenant)
                  if self.qos is not None else None)
        # BOUNDED acquire: a migrating drain can run in both
        # directions at once (A evacuating into B while B evacuates
        # into A), and each evacuation holds its own step lock while
        # importing into the other — an unbounded acquire here would
        # be that ABBA deadlock. Timing out just skips the KV
        # transfer: the continuation re-prefills (a cache miss).
        if not self._step_lock.acquire(timeout=5.0):
            return 0
        try:
            fill = self.allocator.import_chain(
                list(snap.chain_tokens), namespace=snap.adapter or "",
                tenant=tenant)
            if not fill:
                return 0
            idxs = np.asarray([i for i, _ in fill])
            ids = np.asarray([p for _, p in fill])
            pools = self.state["pools"]
            for name, pool in pools.items():
                src = snap.kv_pages.get(name)
                if src is not None:
                    pools[name] = pool.at[:, ids].set(
                        jnp.asarray(src[:, idxs]))
            draft = self.state.get("draft_pools")
            if draft is not None:
                for name, pool in draft.items():
                    src = snap.kv_pages.get("draft/" + name)
                    if src is not None:
                        draft[name] = pool.at[:, ids].set(
                            jnp.asarray(src[:, idxs]))
            return len(fill)
        finally:
            self._step_lock.release()

    def _evacuate(self, migrate) -> None:
        """drain(migrate=...)'s zero-token-loss evacuation: under ONE
        `_step_lock` hold — so no decode can interleave between a
        snapshot and its release, and no token is ever generated on
        two replicas — snapshot every live slot and pending request
        and offer each to the `migrate(snapshot, request) -> bool`
        callback (the ReplicatedRouter's drain wires this to a
        healthy replica's import). True = evacuated (released here,
        resumed there, handle mirrored by the caller); False or an
        export failure leaves the request in place for the normal
        drain wait. Requests mid-admission finish their (bounded)
        prefill normally."""
        led = self._migration
        with self._step_lock:
            if self._inflight is not None:
                self._commit_inflight()
            job_slots = {s for job in self._jobs for s in job.slots}
            for sid, slot in enumerate(self._slots):
                if slot is None or sid in job_slots:
                    continue
                req = slot.req
                if req._cancel.is_set() or req._done.is_set():
                    continue
                led.record_export_start()
                try:
                    if self._faults is not None:
                        self._faults.check("migrate_export")
                    snap, sid2, committed = (
                        self._export_request_locked(req, "drain"))
                except Exception:
                    led.record_export_failed()
                    continue
                if not migrate(snap, req):
                    led.record_export_failed()
                    continue
                self._evacuate_request_locked(req, sid2, committed)
                led.record_export_done(len(snap.tokens),
                                       snap.n_kv_pages())
            with self._lock:
                pend = list(self._pending)
            for req in pend:
                if req._cancel.is_set():
                    continue
                led.record_export_start()
                try:
                    if self._faults is not None:
                        self._faults.check("migrate_export")
                    snap = self._build_snapshot(req, "drain", (), None)
                except Exception:
                    led.record_export_failed()
                    continue
                if not migrate(snap, req):
                    led.record_export_failed()
                    continue
                try:
                    self._evacuate_request_locked(req, None, [])
                except RuntimeError:
                    # cancelled out of the queue mid-offer; the
                    # destination's copy completes (or cancels) on
                    # its own — nothing was lost here
                    led.record_export_failed()
                    continue
                led.record_export_done(len(snap.tokens), 0)

    def _fail_all(self, exc: BaseException) -> None:
        # BOUNDED step-lock acquire: teardown serializes against any
        # concurrent step() (another thread may be mid-iteration when
        # stop() gives up on a drain), so slot state is never torn
        # down under a live dispatch — but a scheduler thread WEDGED
        # inside a dispatch (device hang) still holds _step_lock, and
        # failing everyone must unblock waiters rather than hang with
        # it, so after the timeout teardown proceeds unserialized
        # (nothing else will ever release that lock). The crashed
        # serve_forever path acquires instantly — its step() exited.
        got = self._step_lock.acquire(
            timeout=self._teardown_lock_timeout_s)
        if not got:
            # make the unserialized teardown VISIBLE: before this
            # counter, a timed-out acquire proceeded with no trace
            # that slot state was torn down against a possibly-live
            # dispatch (cloud_server_unserialized_teardown_total)
            self.unserialized_teardowns += 1
        try:
            with self._lock:
                pending, self._pending = (list(self._pending),
                                          collections.deque())
            for sid in range(self.max_slots):
                if self._slots[sid] is not None:
                    # keyed_tokens=[] — drops the refs (keeping the
                    # allocator consistent for any future recovery
                    # path) but keys NOTHING: a failed dispatch may
                    # have left these pages half-written, so they must
                    # not enter the prefix cache as valid KV
                    slot = self._release_slot(sid, [])
                    slot.req.finish_reason = f"error: {exc!r}"
                    self._complete(slot.req)
            self._jobs.clear()
            # async scheduler: drop the launched-but-uncommitted
            # dispatch's futures (its results belong to requests that
            # just failed; like the wedged-teardown case, any still-
            # running device work finishes into buffers nothing reads)
            self._inflight = None
            self._reaped.clear()
        finally:
            if got:
                self._step_lock.release()
        for req in pending:
            if self.qos is not None:
                self.qos.on_pending_removed(req.tenant)
            req.finish_reason = f"error: {exc!r}"
            self._complete(req)

    def serve_forever(self, idle_sleep_s: float = 0.05) -> None:
        while not self._stop.is_set():
            try:
                busy = self.step()
            except Exception as exc:  # noqa: BLE001 — must not hang clients
                import traceback
                traceback.print_exc()
                self._fail_all(exc)
                self._stop.set()
                return
            # cooperative yield after every busy step: the sequential
            # loop's blocking device_get released the GIL for a whole
            # device step each iteration, guaranteeing stream-consumer
            # threads (SSE writers, result() waiters) a drain window;
            # the pipelined loop's syncs can return instantly, so
            # without an explicit yield a fast scheduler can emit a
            # whole request before a streaming client's writer thread
            # runs once — delaying disconnect detection to the end
            if busy:
                time.sleep(0)
            # analysis: allow[lock-discipline] idle-polling read on the
            # scheduler's own thread — the only _jobs writer
            if busy == 0 and self.num_pending == 0 and not self._jobs:
                # bounded CONDITION wait, not a short sleep poll: an
                # idle fleet must not spin step() hundreds of times a
                # second (the idle_iterations_total growth-rate
                # regression test pins this). submit() notifies _work,
                # so admission latency never pays the timeout; the
                # timeout itself keeps pending-deadline sweeps and
                # stop() responsive even if a notify is missed.
                with self._work:
                    if not self._pending and not self._stop.is_set():
                        self._work.wait(idle_sleep_s)

    def start(self) -> "PagedInferenceServer":
        self._stop.clear()
        with self._lock:
            # under the state lock like every other _draining flip: a
            # stopped-then-restarted server serves again, and a racing
            # submit sees either verdict cleanly, never a torn latch
            self._draining = False
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="paged-inference-server")
        self._thread.start()
        return self

    def drain(self, timeout: float | None = None, *, migrate=None,
              _resume_on_timeout: bool = True) -> bool:
        """Graceful drain: refuse new submissions, let everything
        already accepted run to completion. Returns True once idle —
        and STAYS draining (quiesced): call resume() to accept again,
        or stop() to shut down. On timeout returns False and RESUMES
        accepting (the in-flight work keeps running; call stop() to
        actually shut down — it fails whatever is still live so no
        waiter hangs). Safe with or without the background scheduler
        thread. `_resume_on_timeout=False` is stop(drain=True)'s
        internal latch: a timed-out drain there must NOT reopen
        submission in the window before _stop is set, or a request
        could be accepted just to be failed.

        `migrate` turns the drain into a zero-token-loss EVACUATION:
        a `migrate(snapshot, request) -> bool` callback (see
        `_evacuate`; `ReplicatedRouter.drain(migrate=True)` builds
        one) is offered every live request, and each accepted offer
        moves the request to another replica instead of waiting it
        out. Whatever the callback declines drains normally."""
        with self._lock:
            self._draining = True
        if migrate is not None:
            self._evacuate(migrate)
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)

        def busy() -> bool:
            # analysis: allow[lock-discipline] idle-polling bool() of a
            # GIL-atomic list; drain only needs eventual quiescence
            return bool(self.num_pending or self.num_active or self._jobs)

        while busy():
            if deadline is not None and time.perf_counter() > deadline:
                if _resume_on_timeout:
                    with self._lock:
                        self._draining = False
                return False
            if self._thread is None:
                self.step()
            else:
                time.sleep(0.002)
        return True

    def resume(self) -> None:
        """Clear a successful drain's quiesce: accept submissions again
        (no thread restart needed — the scheduler never stopped)."""
        with self._lock:
            self._draining = False

    def stop(self, drain: bool = False,
             timeout: float | None = None) -> None:
        if drain and not self._stop.is_set():
            # keep _draining latched across a timed-out drain: between
            # drain() returning False and _stop.set() below, a submit()
            # must be rejected, not accepted-then-failed by _fail_all
            self.drain(timeout, _resume_on_timeout=False)
        self._stop.set()
        with self._lock:
            # wake a scheduler thread parked on the idle condition
            # wait so shutdown does not pay the wait timeout
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # analysis: allow[lock-discipline] post-join read: the scheduler
        # thread is dead (or never ran) by this point
        if self.num_pending or self.num_active or self._jobs:
            # a timed-out (or skipped) drain left live requests behind:
            # nothing will ever step them now — unblock their waiters
            # (_fail_all drops page refs without caching them, which is
            # the conservative teardown for possibly-mid-write KV)
            self._fail_all(RuntimeError(
                "server stopped before the request completed"))

"""Multi-LoRA serving: many adapters live on one base model, selected
PER REQUEST (cf. vLLM's multi-LoRA, re-built for XLA's static shapes).

Design: all registered adapters stack into one device tensor per target
— A: (N+1, L, fan_in, r_max), B: (N+1, L, r_max, fan_out) — with row 0
the NULL adapter (zeros: delta exactly 0) and ranks zero-padded to the
set's max (padding contributes nothing to A@B). Each slot of the
continuous batch carries an adapter id; every dispatch gathers its
per-row (a, b, scale) and the model applies the low-rank delta at the
same points a merged weight would land
(`transformer.lora_row_delta` — before rope for wq/wk, on the flattened
head output for wo, around swiglu for the mlp). Unadapted slots ride
id 0 and are bit-identical to the base model; mixing adapters in one
batch costs two thin einsums per target per layer, no recompiles, no
weight swapping.

Dense targets only (wq/wk/wv/wo/w_gate/w_up/w_down); adapters may
target different subsets and use different ranks/alphas.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(multi-adapter serving).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.models.lora import _DENSE_TARGETS, LoRAConfig


class AdapterSet:
    """Registry + stacked device tensors for per-request LoRA serving.

    `add` returns the adapter id (>= 1; 0 is the null adapter) and
    restacks the device tensors — a rare, admission-path operation.
    """

    def __init__(self, model_cfg: ModelConfig, mesh=None):
        self.model_cfg = model_cfg
        self.mesh = mesh
        self._names: list[str] = []
        self._ids: dict[str, int] = {}
        self._raw: list[tuple[dict, LoRAConfig]] = []
        self.stacks: dict | None = None  # {target: {"a","b"}} device
        self.scales: jnp.ndarray | None = None  # (cap,) f32
        # admission-cost amortization: stacks carry CAPACITY rows
        # (geometric growth) and a rank headroom, so a typical add is
        # one device row-scatter of the new adapter — not an O(total
        # adapter bytes) host restack + re-upload per registration
        self._cap = 0     # allocated adapter rows incl. the null row
        self._r_cap = 0   # allocated rank (stacks' r dimension)
        self.rebuilds = 0  # full restacks performed (observability)

    def __len__(self) -> int:
        return len(self._names)

    @property
    def names(self) -> list[str]:
        return list(self._names)

    def adapter_id(self, name: str) -> int | None:
        return self._ids.get(name)

    def add(self, name: str, lora_params: dict, lora_cfg: LoRAConfig
            ) -> int:
        if name in self._ids:
            raise ValueError(f"adapter {name!r} already registered")
        bad = set(lora_cfg.targets) - set(_DENSE_TARGETS)
        if bad:
            raise ValueError(
                f"multi-LoRA serving supports dense targets only; "
                f"{sorted(bad)} are not servable per-request")
        layers = lora_params.get("layers", lora_params)
        missing = set(lora_cfg.targets) - set(layers)
        if missing:
            raise ValueError(f"adapter {name!r} missing params for "
                             f"targets {sorted(missing)}")
        # validate against the MODEL's shapes: a self-consistent but
        # wrong-sized adapter would otherwise register fine and explode
        # (or kill the scheduler) at the first dispatch
        from cloud_server_tpu.models.lora import _split_dims
        from cloud_server_tpu.models.transformer import param_shapes
        shapes = param_shapes(self.model_cfg)["layers"]
        for t in lora_cfg.targets:
            L = shapes[t][0]
            _, fan_in, fan_out = _split_dims(t, shapes[t])
            a = np.asarray(layers[t]["a"])
            b = np.asarray(layers[t]["b"])
            want_a = (L, fan_in, lora_cfg.rank)
            want_b = (L, lora_cfg.rank, fan_out)
            if a.shape != want_a or b.shape != want_b:
                raise ValueError(
                    f"adapter {name!r} target {t!r}: a{a.shape}/"
                    f"b{b.shape} do not match the base model's "
                    f"{want_a}/{want_b}")
        # TRANSACTIONAL: validation above is complete, so the fast path
        # can mutate safely; the rebuild path builds from a candidate
        # list first — a failure leaves the registry untouched (a
        # half-registered name would pass submit()'s validation and
        # clamp-gather some other adapter's weights)
        new_id = len(self._raw) + 1
        raw2 = self._raw + [(layers, lora_cfg)]
        fits = (self.stacks is not None
                and new_id + 1 <= self._cap
                and lora_cfg.rank <= self._r_cap)
        if fits:
            self._write_row(new_id, layers, lora_cfg)
        else:
            try:
                self._rebuild(raw2)  # with geometric headroom
            except (ValueError, TypeError) as exc:
                raise ValueError(
                    f"adapter {name!r} has inconsistent shapes: {exc}"
                ) from exc
        self._names.append(name)
        self._ids[name] = new_id  # id 0 = null adapter
        self._raw = raw2
        return new_id

    def _put(self, x):
        if self.mesh is None:
            return jnp.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(jnp.asarray(x),
                              NamedSharding(self.mesh, P()))

    def _zero_stack(self, t: str) -> dict[str, jnp.ndarray]:
        """Capacity-sized all-zero (= null-adapter) stacks for one
        target, shaped from the base model."""
        from cloud_server_tpu.models.lora import _split_dims
        from cloud_server_tpu.models.transformer import param_shapes
        shape = param_shapes(self.model_cfg)["layers"][t]
        L = shape[0]
        _, fan_in, fan_out = _split_dims(t, shape)
        return {"a": self._put(jnp.zeros((self._cap, L, fan_in,
                                          self._r_cap), jnp.float32)),
                "b": self._put(jnp.zeros((self._cap, L, self._r_cap,
                                          fan_out), jnp.float32))}

    def _write_row(self, i: int, layers: dict, cfg: LoRAConfig) -> None:
        """O(one adapter) admission: scatter the new adapter's rows into
        the device stacks (a target nobody used yet gets a fresh zero
        stack first — earlier adapters' rows in it are correctly the
        null adapter). The H2D traffic is the new adapter's bytes; the
        on-device buffer copy rides HBM bandwidth.

        Built on COPIES and swapped in at the end: the scheduler thread
        may be flattening device_args()' current dict for a dispatch
        right now (it holds _step_lock, not the registry lock), so the
        live containers must never mutate under a reader."""
        stacks = {t: dict(ab) for t, ab in self.stacks.items()}
        for t in cfg.targets:
            ab = stacks.get(t) or self._zero_stack(t)
            a = jnp.asarray(np.asarray(layers[t]["a"], np.float32))
            b = jnp.asarray(np.asarray(layers[t]["b"], np.float32))
            stacks[t] = {
                "a": ab["a"].at[i, :, :, :cfg.rank].set(a),
                "b": ab["b"].at[i, :, :cfg.rank, :].set(b)}
        scales = self.scales.at[i].set(cfg.scale)
        self.stacks = stacks
        self.scales = scales

    def _rebuild(self, raw) -> None:
        """Full restack (first add, capacity exhausted, or a rank above
        the allocated headroom): capacity doubles so rebuilds amortize
        to O(1) restacked rows per add."""
        self.rebuilds += 1
        r_max = max(cfg.rank for _, cfg in raw)
        targets = sorted({t for _, cfg in raw for t in cfg.targets})
        n = len(raw) + 1
        cap = r_cap = 1
        while cap < max(n, 4):
            cap *= 2
        while r_cap < r_max:
            r_cap *= 2
        stacks: dict[str, dict[str, np.ndarray]] = {}
        for t in targets:
            # shapes from the first adapter carrying the target
            ref = next(layers[t] for layers, cfg in raw
                       if t in cfg.targets)
            L, fan_in, _ = np.asarray(ref["a"]).shape
            fan_out = np.asarray(ref["b"]).shape[-1]
            a = np.zeros((cap, L, fan_in, r_cap), np.float32)
            b = np.zeros((cap, L, r_cap, fan_out), np.float32)
            for i, (layers, cfg) in enumerate(raw, start=1):
                if t in cfg.targets:
                    a[i, :, :, :cfg.rank] = np.asarray(layers[t]["a"],
                                                       np.float32)
                    b[i, :, :cfg.rank, :] = np.asarray(layers[t]["b"],
                                                       np.float32)
            stacks[t] = {"a": a, "b": b}
        scales = np.zeros((cap,), np.float32)
        scales[0] = 1.0
        scales[1:n] = [cfg.scale for _, cfg in raw]
        self.stacks = jax.tree.map(self._put, stacks)
        self.scales = self._put(scales)
        self._cap = cap
        self._r_cap = r_cap

    def device_args(self):
        """(stacks, scales) to pass into a dispatch (None when empty)."""
        if not self._raw:
            return None
        return (self.stacks, self.scales)


def layer_lora(adapters, aid: jnp.ndarray, layer_idx: int):
    """Per-layer, per-row adapter gather for `transformer.*(lora=...)`.

    adapters: (stacks, scales) from AdapterSet.device_args; aid: (B,)
    int32 adapter ids. Returns {target: (a (B, fan_in, r),
    b (B, r, fan_out), scale (B,))}."""
    if adapters is None:
        return None
    stacks, scales = adapters
    s = scales[aid]
    return {t: (ab["a"][aid, layer_idx], ab["b"][aid, layer_idx], s)
            for t, ab in stacks.items()}

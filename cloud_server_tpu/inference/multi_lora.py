"""Multi-LoRA serving: many adapters live on one base model, selected
PER REQUEST (cf. vLLM's multi-LoRA, re-built for XLA's static shapes).

Design: all registered adapters stack into one device tensor per target
— A: (N+1, L, fan_in, r_max), B: (N+1, L, r_max, fan_out) — with row 0
the NULL adapter (zeros: delta exactly 0) and ranks zero-padded to the
set's max (padding contributes nothing to A@B). Each slot of the
continuous batch carries an adapter id; every dispatch gathers its
per-row (a, b, scale) and the model applies the low-rank delta at the
same points a merged weight would land
(`transformer.lora_row_delta` — before rope for wq/wk, on the flattened
head output for wo, around swiglu for the mlp). Unadapted slots ride
id 0 and are bit-identical to the base model; mixing adapters in one
batch costs two thin einsums per target per layer, no recompiles, no
weight swapping.

Dense targets only (wq/wk/wv/wo/w_gate/w_up/w_down); adapters may
target different subsets and use different ranks/alphas.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(multi-adapter serving).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.models.lora import _DENSE_TARGETS, LoRAConfig


class AdapterSet:
    """Registry + stacked device tensors for per-request LoRA serving.

    `add` returns the adapter id (>= 1; 0 is the null adapter) and
    restacks the device tensors — a rare, admission-path operation.
    """

    def __init__(self, model_cfg: ModelConfig, mesh=None):
        self.model_cfg = model_cfg
        self.mesh = mesh
        self._names: list[str] = []
        self._ids: dict[str, int] = {}
        self._raw: list[tuple[dict, LoRAConfig]] = []
        self.stacks: dict | None = None  # {target: {"a","b"}} device
        self.scales: jnp.ndarray | None = None  # (N+1,) f32

    def __len__(self) -> int:
        return len(self._names)

    @property
    def names(self) -> list[str]:
        return list(self._names)

    def adapter_id(self, name: str) -> int | None:
        return self._ids.get(name)

    def add(self, name: str, lora_params: dict, lora_cfg: LoRAConfig
            ) -> int:
        if name in self._ids:
            raise ValueError(f"adapter {name!r} already registered")
        bad = set(lora_cfg.targets) - set(_DENSE_TARGETS)
        if bad:
            raise ValueError(
                f"multi-LoRA serving supports dense targets only; "
                f"{sorted(bad)} are not servable per-request")
        layers = lora_params.get("layers", lora_params)
        missing = set(lora_cfg.targets) - set(layers)
        if missing:
            raise ValueError(f"adapter {name!r} missing params for "
                             f"targets {sorted(missing)}")
        # validate against the MODEL's shapes: a self-consistent but
        # wrong-sized adapter would otherwise register fine and explode
        # (or kill the scheduler) at the first dispatch
        from cloud_server_tpu.models.lora import _split_dims
        from cloud_server_tpu.models.transformer import param_shapes
        shapes = param_shapes(self.model_cfg)["layers"]
        for t in lora_cfg.targets:
            L = shapes[t][0]
            _, fan_in, fan_out = _split_dims(t, shapes[t])
            a = np.asarray(layers[t]["a"])
            b = np.asarray(layers[t]["b"])
            want_a = (L, fan_in, lora_cfg.rank)
            want_b = (L, lora_cfg.rank, fan_out)
            if a.shape != want_a or b.shape != want_b:
                raise ValueError(
                    f"adapter {name!r} target {t!r}: a{a.shape}/"
                    f"b{b.shape} do not match the base model's "
                    f"{want_a}/{want_b}")
        # TRANSACTIONAL: build the new stacks from a candidate list
        # first — a shape mismatch raises here, leaving the registry
        # untouched (a half-registered name would pass submit()'s
        # validation and clamp-gather some other adapter's weights)
        raw2 = self._raw + [(layers, lora_cfg)]
        try:
            stacks, scales = self._build(raw2)
        except (ValueError, TypeError) as exc:
            raise ValueError(
                f"adapter {name!r} has inconsistent shapes: {exc}"
            ) from exc
        self._names.append(name)
        self._ids[name] = len(self._names)  # id 0 = null adapter
        self._raw = raw2
        self.stacks = stacks
        self.scales = scales
        return self._ids[name]

    def _build(self, raw):
        r_max = max(cfg.rank for _, cfg in raw)
        targets = sorted({t for _, cfg in raw for t in cfg.targets})
        n = len(raw) + 1
        stacks: dict[str, dict[str, np.ndarray]] = {}
        for t in targets:
            # shapes from the first adapter carrying the target
            ref = next(layers[t] for layers, cfg in raw
                       if t in cfg.targets)
            L, fan_in, _ = np.asarray(ref["a"]).shape
            fan_out = np.asarray(ref["b"]).shape[-1]
            a = np.zeros((n, L, fan_in, r_max), np.float32)
            b = np.zeros((n, L, r_max, fan_out), np.float32)
            for i, (layers, cfg) in enumerate(raw, start=1):
                if t in cfg.targets:
                    a[i, :, :, :cfg.rank] = np.asarray(layers[t]["a"],
                                                       np.float32)
                    b[i, :, :cfg.rank, :] = np.asarray(layers[t]["b"],
                                                       np.float32)
            stacks[t] = {"a": jnp.asarray(a), "b": jnp.asarray(b)}
        scales = jnp.asarray([1.0] + [cfg.scale for _, cfg in raw],
                             jnp.float32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            put = lambda x: jax.device_put(  # noqa: E731
                x, NamedSharding(self.mesh, P()))
            stacks = jax.tree.map(put, stacks)
            scales = put(scales)
        return stacks, scales

    def device_args(self):
        """(stacks, scales) to pass into a dispatch (None when empty)."""
        if not self._raw:
            return None
        return (self.stacks, self.scales)


def layer_lora(adapters, aid: jnp.ndarray, layer_idx: int):
    """Per-layer, per-row adapter gather for `transformer.*(lora=...)`.

    adapters: (stacks, scales) from AdapterSet.device_args; aid: (B,)
    int32 adapter ids. Returns {target: (a (B, fan_in, r),
    b (B, r, fan_out), scale (B,))}."""
    if adapters is None:
        return None
    stacks, scales = adapters
    s = scales[aid]
    return {t: (ab["a"][aid, layer_idx], ab["b"][aid, layer_idx], s)
            for t, ab in stacks.items()}

from cloud_server_tpu.inference.sampling import sample_logits  # noqa: F401
from cloud_server_tpu.inference.engine import (  # noqa: F401
    KVCache, encode, generate, init_cache, prefill)
from cloud_server_tpu.inference.beam import beam_search  # noqa: F401
from cloud_server_tpu.inference.server import (  # noqa: F401
    InferenceServer, QueueFullError, Request)
from cloud_server_tpu.inference.router import ReplicatedRouter  # noqa: F401
from cloud_server_tpu.inference.http_server import HttpFrontend  # noqa: F401

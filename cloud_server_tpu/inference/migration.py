"""Live request migration: checkpoint/restore for mid-stream failover
and zero-loss drains.

A migration moves ONE in-flight request from a failing (or draining)
replica to a healthy one without losing or duplicating a single
delivered token.  The snapshot is taken at the scheduler's sanctioned
commit point — the only moment the host's view of the request (tokens,
KV watermark, grammar walker, RNG position) is exact — and carries:

  * the generated-token stream (plus logprobs/emit timestamps so the
    mirrored handle is indistinguishable from an uninterrupted one),
  * the request's KV pages for every COMMITTED full page, gathered
    with the export's one sanctioned ``device_get`` (off the plan
    path, so the dispatch-discipline DD5 invariant holds),
  * the sampling RNG position.  PR 9's streams are position-keyed
    (``fold_in(seed, position)``), so "RNG state" is just
    ``seed_used`` — the destination re-derives every future stream
    from the seed and the token index, no generator state crosses,
  * grammar progress, implicitly: the destination re-walks the
    generated tokens through its own compiled walker (the walk is
    deterministic, so the resumed ``gstate`` is exact),
  * identity and budget: tenant, adapter, QoS/SLO class, and the
    deadline REMAINDER (absolute deadlines are per-host monotonic
    clocks and must not cross machines),
  * trace context, so the destination's spans join the source's tree
    — one gap-free trace across replicas.

Import is deliberately thin: the destination scatters the pages back
into its pool under the radix chain keys (shared prefixes dedupe on
arrival — an imported page whose key is already cached is dropped,
not duplicated) and then re-admits the request through the NORMAL
continuation-admission path.  Token exactness therefore never depends
on the KV transfer: the pages are a prefill-cost optimization, and a
partially-imported (or evicted-on-arrival) chain is just a cache miss.

This module is host policy: stdlib-only (DD3), lock discipline
checked (the ledger's lock is leaf-level), and its record hooks ride
the scheduler hot path so they are on the hot-path lint roster.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

# Snapshot wire-format version: bump when fields change incompatibly.
# import paths reject snapshots from a different major version rather
# than resuming a request from misread state.
MIGRATION_VERSION = 1


@dataclasses.dataclass(frozen=True)
class MigrationSnapshot:
    """Everything needed to resume one request on another replica.

    Built by ``PagedInferenceServer.migrate_export`` (live, with KV)
    or ``migrate_salvage`` (crash path, host state only — the dead
    scheduler already released its pages).  Arrays in ``kv_pages``
    are opaque to this module (host buffers produced by the export's
    sanctioned sync); everything else is plain Python data.
    """

    version: int
    request_id: str
    reason: str                       # "failover" | "drain" | ...
    prompt: tuple
    tokens: tuple                     # generated so far (delivered)
    logprobs: tuple
    emit_times: tuple
    seed_used: Any                    # RNG position key: seed only
    sampling: Any                     # SamplingParams (carries grammar
                                      # regex; walker state re-derived
                                      # by walking `tokens`)
    adapter: Any
    tenant: Any
    slo_class: Any
    max_new_tokens: int
    deadline_remaining_s: float | None
    trace_ctx: tuple | None           # (trace_id, root_span_id, True)
    chain_tokens: tuple               # committed stream covered by
                                      # the exported full pages
    kv_pages: dict | None             # pool name -> host array of the
                                      # chain's full pages, or None
                                      # (crash-path salvage)

    def remaining_new_tokens(self) -> int:
        """Decode budget left after the tokens already generated."""
        return max(0, int(self.max_new_tokens) - len(self.tokens))

    def full_prompt(self) -> tuple:
        """Continuation prompt: original prompt + generated stream."""
        return tuple(self.prompt) + tuple(self.tokens)

    def n_kv_pages(self) -> int:
        """Full pages carried by the snapshot (0 for salvage)."""
        if not self.kv_pages:
            return 0
        for arr in self.kv_pages.values():
            return int(arr.shape[1])
        return 0


class MigrationLedger:
    """Lock-guarded migration counters for one server.

    Record hooks are int adds under a leaf lock — they run from the
    export/import paths (which hold the scheduler's ``_step_lock``)
    and must never block or allocate.  ``stats()`` is the read side
    surfaced on ``/stats`` and merged fleet-wide by the router.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.out_started = 0
        self.out_completed = 0
        self.out_failed = 0
        self.in_started = 0
        self.in_completed = 0
        self.in_failed = 0
        self.tokens_salvaged = 0
        self.pages_moved = 0
        # per-iteration deltas consumed by the flight recorder
        # (migrated_in/out counts on the iteration record)
        self._flight_in = 0
        self._flight_out = 0

    def record_export_start(self) -> None:
        with self._lock:
            self.out_started += 1

    def record_export_done(self, n_tokens: int, n_pages: int) -> None:
        with self._lock:
            self.out_completed += 1
            self.tokens_salvaged += int(n_tokens)
            self.pages_moved += int(n_pages)
            self._flight_out += 1

    def record_export_failed(self) -> None:
        with self._lock:
            self.out_failed += 1

    def record_import_start(self) -> None:
        with self._lock:
            self.in_started += 1

    def record_import_done(self) -> None:
        with self._lock:
            self.in_completed += 1
            self._flight_in += 1

    def record_import_failed(self) -> None:
        with self._lock:
            self.in_failed += 1

    def drain_flight_deltas(self) -> tuple:
        """(migrated_in, migrated_out) since the last call — consumed
        once per iteration by the flight recorder."""
        with self._lock:
            out = (self._flight_in, self._flight_out)
            self._flight_in = 0
            self._flight_out = 0
            return out

    def stats(self) -> dict:
        with self._lock:
            started = self.out_started + self.in_started
            completed = self.out_completed + self.in_completed
            failed = self.out_failed + self.in_failed
            return {
                "out_started": self.out_started,
                "out_completed": self.out_completed,
                "out_failed": self.out_failed,
                "in_started": self.in_started,
                "in_completed": self.in_completed,
                "in_failed": self.in_failed,
                "started": started,
                "completed": completed,
                "failed": failed,
                "tokens_salvaged": self.tokens_salvaged,
                "pages_moved": self.pages_moved,
            }

"""Per-request distributed tracing: span trees across the serving fleet.

PR 3's histograms say a deployment's p99 TTFT regressed; they cannot
say where ONE slow request's time went — queue? a preemption gap? a
starved prefill chunk? a slow decode segment on one replica? This
module is the Dapper-style answer (Sigelman et al., 2010): every
sampled request carries a span tree covering its whole lifecycle,
stitched across the router/replica boundary by W3C trace context, and
cross-linked to the scheduler flight recorder by iteration index so a
slow span answers "what else was the scheduler doing right then" in
one hop.

Design rules (the same ones the metrics layer lives by):

  * **Zero new device work.** Every span timestamp is a host moment
    the scheduler already owns — `Request.events`, `emit_times`, and
    the per-iteration `t0`/`now` pair the flight recorder already
    reads. Recording a span is one list append; the tree itself is
    built lazily on the READ path (`/debug/requests/<id>`), never the
    serving path. The dispatch-count regression test runs with
    tracing enabled at 100% sampling, and the `analysis/` hot-path
    lint covers the record path.
  * **Head-based sampling.** The sample decision is made once at
    submit, deterministically from the trace id, so every replica of
    a fleet (and every retry of a client) agrees without
    coordination. An incoming `traceparent` header's sampled flag
    overrides the local rate in either direction (parent-based
    sampling, the W3C convention).
  * **One tree per request, preemption included.** A preempted
    request's tree keeps its identity across requeue/re-admission:
    the gap shows as an explicit `preempt_gap` phase and the phases
    stay contiguous (gap-free) from submit to finish.

Span taxonomy. Phase spans are DERIVED from the lifecycle event trail
(they partition submit → finish with no gaps):

    request                      the root span (whole lifecycle)
      queue                      submit → first admission
      prefill                    admission → (resumed) first token
      decode                     tokens streaming out
      preempt_gap                preempt-requeue → re-admission
      emit                       last token surfaced → finish

The paged scheduler additionally RECORDS iteration-granular spans
(`prefill_chunk`, `decode_segment`), each tagged with the flight
recorder iteration index, slot, and token counts; the router records
`router_pick` (tagged with the replica index) so a fleet-routed
request yields one tree spanning pick → replica execution.

Exports: `GET /debug/requests/<id>` returns one tree as JSON;
`GET /traces` renders the sampled ring in the Chrome trace event
format (load into Perfetto / chrome://tracing); `traceparent` headers
propagate in and out of the HTTP front-end.
"""

from __future__ import annotations

import collections
import threading
import uuid

# The contiguous, gap-free lifecycle phases `request_phases` derives.
PHASES = ("queue", "prefill", "decode", "preempt_gap", "migrate_gap",
          "emit")

TRACEPARENT_HEADER = "traceparent"
_FLAG_SAMPLED = 0x01


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 lowercase hex chars (16 bytes)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]  # 8 bytes


def parse_traceparent(header) -> tuple[str, str, bool] | None:
    """W3C `traceparent` -> (trace_id, parent_span_id, sampled), or
    None for anything malformed (a bad header must degrade to "start a
    fresh trace", never to a 500)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    ver, tid, pid, flags = parts[0], parts[1], parts[2], parts[3]
    if len(ver) != 2 or len(tid) != 32 or len(pid) != 16 or len(flags) < 2:
        return None
    try:
        int(ver, 16)
        int(tid, 16)
        int(pid, 16)
        fl = int(flags[:2], 16)
    except ValueError:
        return None
    if ver.lower() == "ff" or tid == "0" * 32 or pid == "0" * 16:
        return None  # invalid per spec
    return tid.lower(), pid.lower(), bool(fl & _FLAG_SAMPLED)


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


class RequestTrace:
    """Per-request trace state: identity (trace id, root span id, the
    remote parent span when the request arrived with a `traceparent`)
    plus the explicitly recorded spans (iteration-granular scheduler
    spans, router_pick). Phase spans are NOT stored — they derive from
    the request's own event trail at read time, so the serving path
    pays nothing for them."""

    __slots__ = ("trace_id", "root_span_id", "parent_span_id",
                 "request_id", "tags", "spans")

    def __init__(self, request_id: str, trace_id: str | None = None,
                 parent_span_id: str | None = None):
        self.trace_id = trace_id or new_trace_id()
        self.root_span_id = new_span_id()
        self.parent_span_id = parent_span_id
        self.request_id = request_id
        self.tags: dict = {}
        self.spans: list[dict] = []

    def add_span(self, name: str, start: float, end: float,
                 **tags) -> None:
        """Record one finished span (O(1) append; the hot-path lint
        covers this — no clocks are read here, callers pass host
        moments they already had)."""
        self.spans.append({"name": name, "start": start, "end": end,
                           "tags": tags})

    def annotate(self, **tags) -> None:
        """Attach tags to the root span (replica index, tenant)."""
        self.tags.update(tags)


def request_phases(req) -> list[dict]:
    """Contiguous lifecycle phase spans derived from `req.timeline()`
    and `req.emit_times`: queue / prefill / decode / preempt_gap /
    emit, partitioning submit → finish with no gaps (each phase starts
    exactly where the previous one ends). A still-in-flight request's
    last phase has `end: None`.

    Preemption continuity: first_token is only evented once, so the
    prefill → decode boundary after a re-admission is the first emit
    timestamp following that admission (the continuation's resume
    token surfaces at activation)."""
    events = req.timeline()
    emits = list(req.emit_times)
    if not events:
        return []

    def first_emit_in(lo: float, hi: float) -> float | None:
        for e in emits:
            if lo < e <= hi:
                return e
        return None

    phases: list[tuple[str, float, float | None]] = []
    cur: str | None = None
    t_prev = events[0][1]
    for name, t in events:
        if name == "submit":
            cur, t_prev = "queue", t
        elif name == "admit":
            if cur is not None:
                phases.append((cur, t_prev, t))
            cur, t_prev = "prefill", t
        elif name == "first_token":
            if cur == "prefill":
                phases.append(("prefill", t_prev, t))
                cur, t_prev = "decode", t
        elif name == "preempt_requeue":
            if cur == "prefill":
                e = first_emit_in(t_prev, t)
                if e is not None:
                    phases.append(("prefill", t_prev, e))
                    phases.append(("decode", e, t))
                else:
                    phases.append(("prefill", t_prev, t))
            elif cur is not None:
                phases.append((cur, t_prev, t))
            cur, t_prev = "preempt_gap", t
        elif name.startswith("finish:"):
            if cur == "prefill":
                # a re-admitted continuation may finish without a new
                # first_token event: its resume emit is the boundary
                e = first_emit_in(t_prev, t)
                if e is not None:
                    phases.append(("prefill", t_prev, e))
                    cur, t_prev = "decode", e
            if cur == "decode" and emits and t_prev <= emits[-1] <= t:
                phases.append(("decode", t_prev, emits[-1]))
                cur, t_prev = "emit", emits[-1]
            if cur is not None:
                phases.append((cur, t_prev, t))
            cur = None
    if cur is not None:  # in flight: last phase still open
        phases.append((cur, t_prev, None))
    return [{"name": n, "start": a, "end": b} for n, a, b in phases]


class _FinishedTrace:
    """What the ring retains for a COMPLETED request: the trace, the
    (now final) event trail and emit timestamps, and the few scalar
    tags the tree needs — NOT the Request itself, whose prompt /
    token / logprob lists would otherwise keep up to capacity x
    max_context of dead state alive purely for trace export. The
    trace object is shared by reference, so iteration spans stamped
    at the end of the finishing step still land in the tree.
    `trace` overrides which trace the snapshot exports (the tail ring
    passes the provisional `req.tail_trace`)."""

    __slots__ = ("request_id", "trace", "submit_time", "tenant",
                 "finish_reason", "num_tokens", "_events",
                 "emit_times")

    def __init__(self, req, trace=None):
        self.request_id = req.request_id
        self.trace = trace if trace is not None else req.trace
        self.submit_time = req.submit_time
        self.tenant = req.tenant
        self.finish_reason = req.finish_reason
        self.num_tokens = len(req.tokens)
        self._events = req.timeline()
        self.emit_times = req.emit_times  # append-complete at finish

    def timeline(self):
        return list(self._events)


def any_trace(req):
    """The request's head-sampled trace, else its provisional tail
    trace, else None — annotation sites (router failover/handoff
    tagging) must tag whichever tree may eventually be retained."""
    tr = getattr(req, "trace", None)
    return tr if tr is not None else getattr(req, "tail_trace", None)


def continuation_ctx(req) -> tuple[str, str, bool] | None:
    """The (trace_id, parent_span_id, sampled) context a failover /
    handoff continuation submits with so it rejoins the original's
    trace: the head-sampled trace when present (sampled=True, the
    existing contract), else the provisional tail trace with
    sampled=False — the continuation stays head-unsampled but keeps
    the SHARED trace id, so when both halves tail-retain they merge
    into one spanning tree (`merge_handoff_trees` keys on it)."""
    tr = getattr(req, "trace", None)
    if tr is not None:
        return (tr.trace_id, tr.root_span_id, True)
    tr = getattr(req, "tail_trace", None)
    if tr is not None:
        return (tr.trace_id, tr.root_span_id, False)
    return None


def build_tree(req) -> dict | None:
    """The request's span tree as a plain JSON-ready dict (the
    `/debug/requests/<id>` payload) — `req` is a live Request or the
    ring's _FinishedTrace snapshot. None for unsampled requests.
    Recorded scheduler spans nest under the phase whose window
    contains their start; spans that precede submit (router_pick)
    attach directly to the root."""
    tr = getattr(req, "trace", None)
    if tr is None:
        return None
    events = req.timeline()
    start = (req.submit_time if req.submit_time is not None
             else (events[0][1] if events else 0.0))
    end = (events[-1][1]
           if events and events[-1][0].startswith("finish:") else None)
    phases = [dict(p, children=[]) for p in request_phases(req)]

    def owner(ts: float):
        for ph in phases:
            if ts >= ph["start"] and (ph["end"] is None
                                      or ts < ph["end"]):
                return ph
        return None

    loose: list[dict] = []
    for s in sorted(tr.spans, key=lambda s: s["start"]):
        ph = owner(s["start"])
        (ph["children"] if ph is not None else loose).append(dict(s))
    tags = dict(tr.tags)
    if req.tenant is not None:
        tags.setdefault("tenant", req.tenant)
    if req.finish_reason is not None:
        tags["finish_reason"] = req.finish_reason
    n_tok = getattr(req, "num_tokens", None)
    tags["tokens"] = len(req.tokens) if n_tok is None else n_tok
    return {
        "trace_id": tr.trace_id,
        "request_id": req.request_id,
        "root_span_id": tr.root_span_id,
        "parent_span_id": tr.parent_span_id,
        "root": {"name": "request", "start": start, "end": end,
                 "tags": tags, "children": loose + phases},
    }


# Tail-retention reasons, in decision-priority order: the first
# matching clause names the retention (`tail_retained_total{reason=}`
# label values and the docs predicate table key off this tuple).
TAIL_REASONS = ("failed", "deadline", "cancelled", "migrated", "slo",
                "preempt", "anomaly")


class TraceRecorder:
    """Head-sampled per-request trace store: a dict of in-flight
    sampled requests plus a bounded ring of finished ones (oldest
    evicted). Both servers consult it at submit (`begin`) and at
    request completion (`finish`); everything else — lookup, the ring
    export — runs on the read path.

    Tail-based retention (`tail_capacity` > 0): every head-UNSAMPLED
    request still gets a provisional lightweight trace (identity +
    tags only — the schedulers skip iteration-span recording for it,
    so the provisional cost is one small object at submit). At finish
    the provisional tree is RETAINED into a separate bounded tail
    ring iff the request proved anomalous: it failed / deadline-
    expired / was cancelled, was migrated / retried / handed off,
    missed its class SLO target, was preempted >= `tail_preempt_min`
    times, or finished inside an open anomaly window. The decision
    reads only request-terminal state and static config, so every
    replica holding a segment of the same merged tree reaches the
    same verdict (router-merged handoff trees stay whole)."""

    def __init__(self, sample_rate: float = 1.0, capacity: int = 256,
                 tail_capacity: int = 0, tail_preempt_min: int = 2):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError("trace sample_rate must be in [0, 1]")
        if capacity <= 0:
            raise ValueError("trace ring capacity must be positive")
        if tail_capacity < 0:
            raise ValueError("trace tail_capacity must be >= 0")
        if tail_preempt_min <= 0:
            raise ValueError("trace tail_preempt_min must be positive")
        self.sample_rate = float(sample_rate)
        self.capacity = int(capacity)
        self.tail_capacity = int(tail_capacity)
        self.tail_preempt_min = int(tail_preempt_min)
        self._lock = threading.Lock()
        self._live: dict[str, object] = {}          # request_id -> Request
        self._ring: collections.deque = collections.deque()
        self._index: dict[str, object] = {}         # ring members by id
        self._tail_ring: collections.deque = collections.deque()
        self._tail_index: dict[str, object] = {}
        self.sampled_total = 0
        self.evicted_total = 0
        self.tail_retained: dict[str, int] = {r: 0 for r in TAIL_REASONS}
        self.tail_evicted_total = 0

    def should_sample(self, trace_id: str) -> bool:
        """Deterministic head decision from the trace id: every holder
        of the same id (other replicas, the retrying client) reaches
        the same verdict with no coordination."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return int(trace_id[:8], 16) < self.sample_rate * 0x100000000

    def begin(self, req, ctx: tuple[str, str, bool] | None = None):
        """Open a trace for a freshly submitted request. `ctx` is a
        parsed incoming traceparent (trace_id, parent_span_id,
        sampled); its sampled flag is authoritative when present
        (parent-based sampling) — without a context the local head
        rate decides. Sets `req.trace` and returns it (None when the
        request is not sampled)."""
        if ctx is not None:
            trace_id, parent_id, sampled = ctx
        else:
            trace_id, parent_id, sampled = new_trace_id(), None, None
        if sampled is None:
            sampled = self.should_sample(trace_id)
        if not sampled:
            if self.tail_capacity > 0:
                # provisional lightweight tree: identity only; the
                # schedulers see req.trace is None and record no
                # iteration spans, so the hot path pays one object
                req.tail_trace = RequestTrace(req.request_id, trace_id,
                                              parent_id)
            return None
        tr = RequestTrace(req.request_id, trace_id, parent_id)
        req.trace = tr
        with self._lock:
            self._live[req.request_id] = req
            self.sampled_total += 1
        return tr

    def _tail_reason(self, req, tr, slo_violated: bool,
                     in_anomaly: bool) -> str | None:
        """First matching TAIL_REASONS clause, else None (drop). All
        inputs are request-terminal state / static config — the same
        verdict on every replica holding this tree's segments."""
        fr = getattr(req, "finish_reason", None) or ""
        if fr.startswith("error"):
            return "failed"
        if fr in ("deadline", "cancelled", "migrated"):
            return fr
        tags = tr.tags
        if ("handoff_of" in tags or "migrate_of" in tags
                or "retry_of" in tags or "migrated_out" in tags):
            return "migrated"
        if slo_violated:
            return "slo"
        n_pre = 0
        for name, _ts in req.timeline():
            if name == "preempt_requeue":
                n_pre += 1
        if n_pre >= self.tail_preempt_min:
            return "preempt"
        if in_anomaly:
            return "anomaly"
        return None

    def finish(self, req, *, slo_violated: bool = False,
               in_anomaly: bool = False) -> None:
        """Move a completed sampled request from the live set into the
        ring (evicting the oldest past capacity). The ring keeps a
        slim _FinishedTrace snapshot, not the Request — the prompt /
        token / logprob lists are released with the request.

        A head-UNSAMPLED request with a provisional tail trace is
        instead judged by the tail-retention predicate: retained into
        the tail ring (exactly once — a racing duplicate finish is
        dropped) or forgotten. `slo_violated` / `in_anomaly` are the
        caller-supplied clauses the recorder cannot derive itself."""
        if getattr(req, "trace", None) is not None:
            done = _FinishedTrace(req)
            with self._lock:
                self._live.pop(req.request_id, None)
                self._ring.append(done)
                self._index[req.request_id] = done
                while len(self._ring) > self.capacity:
                    old = self._ring.popleft()
                    self._index.pop(old.request_id, None)
                    self.evicted_total += 1
            return
        if self.tail_capacity <= 0:
            return
        tr = getattr(req, "tail_trace", None)
        if tr is None:
            return
        reason = self._tail_reason(req, tr, slo_violated, in_anomaly)
        if reason is None:
            return
        tr.annotate(tail_retained=reason)
        done = _FinishedTrace(req, trace=tr)
        with self._lock:
            if req.request_id in self._tail_index:
                return  # concurrent duplicate finish: retain once
            self._tail_ring.append(done)
            self._tail_index[req.request_id] = done
            self.tail_retained[reason] = (
                self.tail_retained.get(reason, 0) + 1)
            while len(self._tail_ring) > self.tail_capacity:
                old = self._tail_ring.popleft()
                self._tail_index.pop(old.request_id, None)
                self.tail_evicted_total += 1

    def lookup(self, request_id: str) -> dict | None:
        """Span tree for one request id (live, head-retained, or
        tail-retained), else None."""
        with self._lock:
            req = (self._live.get(request_id)
                   or self._index.get(request_id)
                   or self._tail_index.get(request_id))
        return None if req is None else build_tree(req)

    def trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the retained ring plus live requests (oldest
        first; `n` bounds from the newest end — n <= 0 means "no
        trees", never "everything", matching /stats' flight-window
        rule)."""
        if n is not None and n <= 0:
            return []
        with self._lock:
            reqs = list(self._ring) + list(self._live.values())
        trees = [t for t in (build_tree(r) for r in reqs)
                 if t is not None]
        trees.sort(key=lambda t: t["root"]["start"])
        return trees if n is None else trees[-n:]

    def tail_trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the tail-retained ring (oldest first; `n`
        bounds from the newest end, n <= 0 means none — the `trees`
        contract)."""
        if n is not None and n <= 0:
            return []
        with self._lock:
            reqs = list(self._tail_ring)
        trees = [t for t in (build_tree(r) for r in reqs)
                 if t is not None]
        trees.sort(key=lambda t: t["root"]["start"])
        return trees if n is None else trees[-n:]

    def tail_stats(self) -> dict:
        """The /stats tail-retention block (scrape path)."""
        with self._lock:
            return {"capacity": self.tail_capacity,
                    "retained": len(self._tail_ring),
                    "retained_total": dict(self.tail_retained),
                    "evicted_total": self.tail_evicted_total}


def chrome_trace(trees: list[dict],
                 anomalies: list[dict] | None = None) -> dict:
    """Render span trees as Chrome trace event format JSON
    (chrome://tracing / Perfetto `ui.perfetto.dev`): one complete
    ("X") event per span, processes = replicas, threads = requests.
    Timestamps are microseconds on the servers' perf_counter
    timebase — relative durations and alignment are what matter.
    `anomalies` (watchdog event dicts: rule/start/end/details,
    optionally replica) render as marker events on a dedicated
    per-replica "anomalies" track, so an open incident window lines
    up against the request spans it covers."""
    events: list[dict] = []
    for tree in trees:
        root = tree["root"]
        pid = int(root["tags"].get("replica", 0))
        tid = int(tree["request_id"][:8], 16) & 0x7FFFFFFF
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid,
                       "args": {"name": f"req {tree['request_id']}"}})

        def emit(span: dict, name: str | None = None) -> None:
            end = span.get("end")
            start = span["start"]
            args = dict(span.get("tags", {}))
            if end is None:
                end = start
                args["open"] = True
            events.append({
                "ph": "X", "name": name or span["name"],
                "ts": start * 1e6, "dur": max(end - start, 0.0) * 1e6,
                "pid": pid, "tid": tid, "args": args})
            for child in span.get("children", ()):
                emit(child)

        emit(root, name=f"request {tree['request_id']}")

    marker_pids: set[int] = set()
    for ev in anomalies or ():
        pid = int(ev.get("replica", 0))
        if pid not in marker_pids:
            marker_pids.add(pid)
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": "anomalies"}})
        start = ev["start"]
        end = ev.get("end")
        args = dict(ev.get("details", {}))
        if end is None:
            end = start
            args["open"] = True
        events.append({
            "ph": "X", "name": f"anomaly:{ev['rule']}",
            "ts": start * 1e6, "dur": max(end - start, 0.0) * 1e6,
            "pid": pid, "tid": 0, "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def resolve_recorder(tracing, sample_rate: float = 0.0,
                     capacity: int = 256, tail_capacity: int = 0
                     ) -> TraceRecorder | None:
    """The one constructor both servers use: `tracing` may be a ready
    TraceRecorder, a sampling rate (float in [0, 1]), None (falling
    back to `InferConfig.trace_sample_rate`), or False — tracing
    force-disabled regardless of the config fallback. `capacity` /
    `tail_capacity` size the finished and tail-retained rings
    (`InferConfig.trace_capacity` / `trace_tail_capacity`). Returns
    None (tracing fully disabled, byte-identical pre-trace
    scheduling) when the effective rate is 0 and tail retention is
    off; a zero rate WITH a tail ring still records — that is the
    "1% head sampling, broken requests always inspectable" mode."""
    if tracing is False:
        return None
    if isinstance(tracing, TraceRecorder):
        return tracing
    rate = float(tracing if tracing is not None else (sample_rate or 0.0))
    if rate <= 0.0 and tail_capacity <= 0:
        return None
    return TraceRecorder(sample_rate=rate, capacity=capacity,
                         tail_capacity=tail_capacity)


def merge_handoff_trees(trees: list[dict]) -> list[dict]:
    """Stitch disaggregation handoffs into ONE spanning tree per
    request.  A handed-off request leaves two partial trees sharing a
    trace id: the prefill replica's half (closed by finish:migrated)
    and the decode continuation, whose root carries
    ``handoff_of=<original request id>``.  This grafts each
    continuation's spans onto its original's tree with a bridging
    ``migrate_gap`` phase covering the export -> re-admission seam, so
    the merged tree partitions [submit, finish] with no holes across
    replicas.  Failover trees (``retry_of`` / ``migrate_of``) are left
    untouched — operators rely on seeing those as distinct attempts.
    Order-preserving no-op when nothing was handed off.  Trees are
    mutated in place; callers pass freshly built dicts."""
    by_id = {t["request_id"]: t for t in trees}
    segments = [t for t in trees
                if t["root"]["tags"].get("handoff_of") in by_id]
    if not segments:
        return trees
    consumed: set[int] = set()
    # Oldest-first so a (rare) chained hop grafts onto the tree its
    # predecessor already merged into.
    for seg in sorted(segments, key=lambda t: t["root"]["start"]):
        base = by_id.get(seg["root"]["tags"]["handoff_of"])
        if (base is None or base is seg
                or base["trace_id"] != seg["trace_id"]):
            continue
        b_root, s_root = base["root"], seg["root"]
        if (b_root["end"] is not None
                and s_root["start"] >= b_root["end"]):
            b_root["children"].append({
                "name": "migrate_gap", "start": b_root["end"],
                "end": s_root["start"], "tags": {"reason": "handoff"},
                "children": []})
        b_root["children"].extend(s_root["children"])
        b_root["end"] = s_root["end"]
        tags, s_tags = b_root["tags"], s_root["tags"]
        for k, v in s_tags.items():
            if k not in ("handoff_of", "replica"):
                tags[k] = v
        if "replica" in s_tags:
            tags["decode_replica"] = s_tags["replica"]
        segs = list(tags.get("handoff_segments", ()))
        segs.append(seg["request_id"])
        tags["handoff_segments"] = segs
        consumed.add(id(seg))
        by_id[seg["request_id"]] = base
    return [t for t in trees if id(t) not in consumed]

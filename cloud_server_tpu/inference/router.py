"""Data-parallel serving scale-OUT: a replica router.

Tensor-parallel serving scales UP inside one mesh
(`PagedInferenceServer(mesh=...)`: params/pools sharded over tp, XLA
collectives on ICI). This module is the other axis: N INDEPENDENT
replicas — each owning a full copy of the weights (on its own device,
submesh, or host) and its own scheduler — behind a single submit().
The router is pure host-side policy; replicas never synchronize with
each other, so throughput and availability scale linearly and a
replica failure sheds only its own in-flight work (the same shape as
the reference stacks' multi-replica deployments: router + N engines,
re-built here without any cross-replica NCCL).

Placement: least-loaded (active + pending), round-robin on ties — the
rotation keeps a cold, empty fleet from piling every request on
replica 0. Tenant-tagged submits (multi-tenant QoS, inference/qos.py)
break ties from the TENANT'S OWN stable home offset instead of the
global rotation: on an un-loaded fleet a tenant's requests land on the
same replica first (radix prefix-cache locality for its prompts) while
load imbalance still dominates the pick the moment it appears. QoS
limits are PER REPLICA (each replica owns an independent registry):
token buckets and max_pending bound a tenant on each replica, so its
fleet-wide ceiling is ~N× the configured value — divide rates by the
replica count when a fleet-wide bound is the intent. Fair-share
weights need no scaling (ratios converge per replica).

The router exposes the submit / num_active / num_pending / start /
stop surface the HTTP front-end expects, so
`HttpFrontend(ReplicatedRouter(...))` serves a fleet unchanged.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(multi-replica serving scale-out).
"""

from __future__ import annotations

import itertools
import threading
import time
import zlib
from typing import Sequence

import jax


class ReplicatedRouter:
    """Route requests across independent serving replicas."""

    def __init__(self, replicas: Sequence):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # submits picked but not yet visible in their replica's pending
        # queue: _pick() counts them so concurrent submitters see fresh
        # load instead of racing into the same replica (the lock is NOT
        # held across the replica's submit() — that can block on model
        # work — so the counter is what bridges the window)
        self._inflight = [0] * len(self.replicas)

    @classmethod
    def over_devices(cls, params, cfg, infer_cfg, *, devices=None,
                     server_cls=None, **srv_kw) -> "ReplicatedRouter":
        """One replica per device, each with its own copy of `params`
        committed there (dp replication: weights duplicated, nothing
        shared). `devices` defaults to every visible device."""
        from cloud_server_tpu.inference.paged_server import (
            PagedInferenceServer)
        server_cls = server_cls or PagedInferenceServer
        devices = list(devices if devices is not None else jax.devices())
        replicas = []
        for d in devices:
            local = jax.tree.map(lambda x: jax.device_put(x, d), params)
            replicas.append(server_cls(local, cfg, infer_cfg, **srv_kw))
        return cls(replicas)

    # -- placement ----------------------------------------------------------

    def _pick(self, *, tenant: str | None = None,
              count_inflight: bool = False) -> int:
        n = len(self.replicas)
        loads = [r.num_active + r.num_pending + inf
                 for r, inf in zip(self.replicas, self._inflight)]
        if tenant is None:
            k = next(self._rr) % n
        else:
            # tenant-affinity tie-break: a stable per-tenant home
            # offset (crc32, not hash() — PYTHONHASHSEED-independent)
            # so an idle fleet serves a tenant from one replica (its
            # prompts hit that replica's radix prefix cache) while
            # least-loaded still wins under any load skew
            k = zlib.crc32(tenant.encode()) % n
        # readiness-aware placement: a draining (or stopped) replica
        # advertises ready=False and stops receiving new work — its
        # in-flight requests finish undisturbed. With the WHOLE fleet
        # unready the pick falls back to all replicas so the submit
        # surfaces the replica's own "draining" refusal instead of an
        # index error.
        cands = [j for j, r in enumerate(self.replicas)
                 if getattr(r, "ready", True)] or list(range(n))
        # least loaded; ties resolve round-robin from k
        i = min(cands, key=lambda j: (loads[j], (j - k) % n))
        if count_inflight:
            self._inflight[i] += 1
        return i

    def submit(self, prompt, **kw):
        t0 = time.perf_counter()
        with self._lock:
            i = self._pick(tenant=kw.get("tenant"), count_inflight=True)
        try:
            req = self.replicas[i].submit(prompt, **kw)
            tr = getattr(req, "trace", None)
            if tr is not None:
                # the fleet half of the request's ONE span tree: the
                # routing decision as an explicit span (pick through
                # replica-submit return) + the replica tag every
                # replica-side span inherits via the root
                tr.annotate(replica=i)
                tr.add_span("router_pick", t0, time.perf_counter(),
                            replica=i)
            return req
        finally:
            # the request is now in the replica's pending queue (or was
            # rejected) — either way its load is visible/settled again
            with self._lock:
                self._inflight[i] -= 1

    def generate(self, prompts, *, max_new_tokens=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    # -- aggregate surface (HTTP front-end compatible) ----------------------

    def embed(self, prompts):
        """Embeddings via the least-loaded replica (same weights
        everywhere, so any replica's answer is THE answer)."""
        with self._lock:
            i = self._pick()
        fn = getattr(self.replicas[i], "embed", None)
        if fn is None:
            raise ValueError(
                "this serving backend does not support embeddings")
        return fn(prompts)

    @property
    def adapters(self):
        """The adapter registry (replica 0's — add_adapter keeps every
        replica's registry identical, so ids/names agree fleet-wide)."""
        return getattr(self.replicas[0], "adapters", None)

    def add_adapter(self, name: str, lora_params, lora_cfg) -> int:
        """Register a LoRA adapter on EVERY replica (requests routed
        anywhere must find it). Returns the (fleet-wide) adapter id."""
        ids = {r.add_adapter(name, lora_params, lora_cfg)
               for r in self.replicas}
        if len(ids) != 1:  # registries diverged (out-of-band adds)
            raise RuntimeError(
                f"adapter {name!r} got inconsistent ids across "
                f"replicas: {sorted(ids)}; register adapters through "
                "the router only")
        return ids.pop()

    @property
    def num_active(self) -> int:
        return sum(r.num_active for r in self.replicas)

    @property
    def num_pending(self) -> int:
        return sum(r.num_pending for r in self.replicas)

    @property
    def ready(self) -> bool:
        """Fleet readiness: True while ANY replica accepts new work
        (a draining replica only removes itself from placement)."""
        return any(getattr(r, "ready", True) for r in self.replicas)

    @property
    def tokens_emitted(self) -> int:
        return sum(r.tokens_emitted for r in self.replicas)

    def metrics_snapshot(self) -> dict:
        """FLEET-wide metrics: every replica's registry snapshot merged
        (histogram buckets add bucket-for-bucket — identical fixed
        ladders by construction — so a dp deployment's /metrics reports
        true fleet percentiles, not replica-0's). The additive gauge
        merge is wrong for RATIO gauges: `tenant_fair_share` (1.0 =
        exactly fair) would read ~N for N fair replicas, so it is
        recomputed from the fleet-merged generated totals
        (tenant_stats), the same rule that function documents.

        The iteration-phase histograms (`iter_phase_ms`, labeled by
        phase) merge bucket-for-bucket like every other histogram —
        identical ms ladders by construction — and the derived
        `host_gap_frac` is deliberately NOT a registered gauge: the
        /stats summary recomputes it from the merged phase sums
        (iteration_profile.profile_summary), so the ratio can never
        be added across replicas by accident."""
        from cloud_server_tpu.utils.serving_metrics import merge_snapshots
        merged = merge_snapshots(
            r.metrics_snapshot() for r in self.replicas
            if hasattr(r, "metrics_snapshot"))
        tstats = self.tenant_stats()
        for key, entry in merged.items():
            if not key.startswith("cloud_server_tenant_fair_share{"):
                continue
            t = (entry.get("labels") or {}).get("tenant")
            if t in tstats:
                entry["value"] = tstats[t]["fair_share"]
        # spec_accept_rate is a RATIO gauge too: recompute from the
        # fleet-merged drafted/accepted totals, never by adding the
        # per-replica rates
        if "cloud_server_spec_accept_rate" in merged:
            sstats = self.speculation_stats()
            merged["cloud_server_spec_accept_rate"]["value"] = (
                sstats.get("accept_rate", 0.0))
        # same rule for the SLO ratio gauges: attainment/burn recompute
        # from the fleet-merged good/total counts, never by adding the
        # per-replica ratios (two 0.99-attaining replicas must read
        # 0.99, not 1.98)
        srep = self.slo_report()
        if srep is not None:
            for key, entry in merged.items():
                if not (key.startswith("cloud_server_slo_attainment{")
                        or key.startswith("cloud_server_slo_burn_rate{")):
                    continue
                lbl = entry.get("labels") or {}
                went = (srep["classes"]
                        .get(lbl.get("class"), {})
                        .get("metrics", {})
                        .get(lbl.get("metric"), {})
                        .get("windows", {})
                        .get(lbl.get("window_s")))
                if went is None:
                    continue
                if "attainment{" in key:
                    att = went["attainment"]
                    entry["value"] = 1.0 if att is None else att
                else:
                    entry["value"] = went["burn_rate"]
        return merged

    @property
    def qos(self):
        """The TenantRegistry view the HTTP front-end resolves API
        keys against (replica 0's — every replica parses the same
        config, so the key map agrees fleet-wide)."""
        return getattr(self.replicas[0], "qos", None)

    def tenant_stats(self) -> dict:
        """FLEET-wide per-tenant stats: every replica's
        TenantRegistry.stats() merged — counters sum, weight/priority
        come from the shared config, and fair_share is recomputed from
        the merged generated totals (a per-replica ratio would not
        average meaningfully)."""
        merged: dict[str, dict] = {}
        for r in self.replicas:
            reg = getattr(r, "qos", None)
            if reg is None:
                continue
            for name, s in reg.stats().items():
                cur = merged.setdefault(name, {
                    "weight": s["weight"], "priority": s["priority"],
                    "pending": 0, "submitted": 0, "rejected": 0,
                    "generated": 0, "preempt_requeues": 0,
                    "prefill_tokens": 0, "spec_drafted": 0,
                    "spec_accepted": 0, "spec_wasted": 0})
                for k in ("pending", "submitted", "rejected",
                          "generated", "preempt_requeues",
                          "prefill_tokens", "spec_drafted",
                          "spec_accepted", "spec_wasted"):
                    cur[k] += s[k]
        from cloud_server_tpu.inference.qos import compute_fair_shares
        shares = compute_fair_shares(
            {name: (s["weight"], float(s["generated"]))
             for name, s in merged.items()})
        for name, s in merged.items():
            s["fair_share"] = shares[name]
        return merged

    def speculation_stats(self) -> dict:
        """FLEET-wide speculation summary (the /stats `speculation`
        source behind the router): drafted/accepted counts sum across
        replicas and `accept_rate` recomputes from the merged totals
        (a per-replica ratio would not average meaningfully —
        exactly the `tenant_fair_share` rule). Per-replica live
        `draft_lens` views are dropped (slot ids are replica-local)."""
        merged: dict = {}
        for r in self.replicas:
            fn = getattr(r, "speculation_stats", None)
            if fn is None:
                continue
            s = fn()
            if not merged:
                merged = {
                    "enabled": s["enabled"], "source": s["source"],
                    "max_drafts": s["max_drafts"],
                    "adaptive": s["adaptive"],
                    "tokens_drafted": 0, "tokens_accepted": 0}
            elif s["enabled"] and not merged["enabled"]:
                # heterogeneous fleet: config metadata must come from a
                # replica that actually speculates, not whichever
                # answered first — otherwise /stats could report
                # source "off" alongside nonzero drafted counts
                merged.update(source=s["source"],
                              max_drafts=s["max_drafts"],
                              adaptive=s["adaptive"])
            merged["enabled"] = merged["enabled"] or s["enabled"]
            merged["tokens_drafted"] += s["tokens_drafted"]
            merged["tokens_accepted"] += s["tokens_accepted"]
        if merged:
            merged["accept_rate"] = (merged["tokens_accepted"]
                                     / max(merged["tokens_drafted"], 1))
        return merged

    def cache_stats(self) -> dict:
        """FLEET-wide KV-cache/memory view (the /debug/cache and
        /stats `cache` source behind the router): pool, prefix, and
        per-tenant COUNTS sum across replicas; `hit_rate` and
        `evictable_frac` recompute from the merged totals (never
        added — the `tenant_fair_share` ratio rule); the hot-prefix
        sketches merge per chain digest (hits sum, so the same system
        prompt hot on two replicas ranks twice as hot fleet-wide —
        the artifact ROADMAP item 3(a)'s prefix-aware `_pick` scores
        against); forensics rings concatenate tagged by replica.
        Returns {} when no replica exposes cache stats."""
        from cloud_server_tpu.inference.cache_telemetry import (
            merge_cache_stats)
        stats = []
        for r in self.replicas:
            fn = getattr(r, "cache_stats", None)
            if fn is not None:
                stats.append(fn())
        return merge_cache_stats(stats)

    def lookup_trace(self, request_id: str) -> dict | None:
        """Span tree for one sampled request, wherever it ran: the
        first replica that knows the id answers, tagged with its
        replica index (router-submitted requests already carry it from
        the router_pick span)."""
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "lookup_trace", None)
            tree = fn(request_id) if fn is not None else None
            if tree is not None:
                tree["root"]["tags"].setdefault("replica", i)
                return tree
        return None

    def trace_trees(self, n: int | None = None) -> list[dict]:
        """FLEET-wide sampled span trees (the /traces source), each
        tagged with its replica index and ordered by root start
        (n <= 0 means "no trees", the recorder's own rule)."""
        if n is not None and n <= 0:
            return []
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "trace_trees", None)
            if fn is None:
                continue
            for tree in fn(n):
                tree["root"]["tags"].setdefault("replica", i)
                out.append(tree)
        out.sort(key=lambda t: t["root"]["start"])
        return out if n is None else out[-n:]

    def slo_report(self) -> dict | None:
        """FLEET-wide SLO attainment + burn rates: every replica's
        report merged by summing good/total counts per (class, metric,
        window) and recomputing the ratios — the control signal the
        future autoscaler consumes. None when no replica tracks
        SLOs."""
        from cloud_server_tpu.inference.slo import merge_reports
        return merge_reports(
            r.slo_report() for r in self.replicas
            if hasattr(r, "slo_report"))

    def flight_window(self, n: int | None = None) -> list[dict]:
        """Recent flight-recorder records across the fleet, each tagged
        with its replica index, ordered by wall-clock timestamp."""
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "flight_window", None)
            if fn is not None:
                out += [{"replica": i, **rec} for rec in fn(n)]
        out.sort(key=lambda rec: rec.get("ts", 0.0))
        return out

    def step(self) -> int:
        busy = 0
        for r in self.replicas:
            busy += r.step()
        return busy

    def run_until_idle(self) -> None:
        while any(r.num_pending or r.num_active
                  or getattr(r, "_jobs", ())
                  for r in self.replicas):
            self.step()

    def start(self) -> "ReplicatedRouter":
        for r in self.replicas:
            r.start()
        return self

    def stop(self, drain: bool = False,
             timeout: float | None = None) -> None:
        for r in self.replicas:
            try:
                r.stop(drain=drain, timeout=timeout)
            except TypeError:  # replica without drain support
                r.stop()

"""Data-parallel serving scale-OUT: a replica router.

Tensor-parallel serving scales UP inside one mesh
(`PagedInferenceServer(mesh=...)`: params/pools sharded over tp, XLA
collectives on ICI). This module is the other axis: N INDEPENDENT
replicas — each owning a full copy of the weights (on its own device,
submesh, or host) and its own scheduler — behind a single submit().
The router is pure host-side policy; replicas never synchronize with
each other, so throughput and availability scale linearly and a
replica failure sheds only its own in-flight work (the same shape as
the reference stacks' multi-replica deployments: router + N engines,
re-built here without any cross-replica NCCL).

Placement: least-loaded (active + pending), round-robin on ties — the
rotation keeps a cold, empty fleet from piling every request on
replica 0.

The router exposes the submit / num_active / num_pending / start /
stop surface the HTTP front-end expects, so
`HttpFrontend(ReplicatedRouter(...))` serves a fleet unchanged.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(multi-replica serving scale-out).
"""

from __future__ import annotations

import itertools
import threading
from typing import Sequence

import jax


class ReplicatedRouter:
    """Route requests across independent serving replicas."""

    def __init__(self, replicas: Sequence):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # submits picked but not yet visible in their replica's pending
        # queue: _pick() counts them so concurrent submitters see fresh
        # load instead of racing into the same replica (the lock is NOT
        # held across the replica's submit() — that can block on model
        # work — so the counter is what bridges the window)
        self._inflight = [0] * len(self.replicas)

    @classmethod
    def over_devices(cls, params, cfg, infer_cfg, *, devices=None,
                     server_cls=None, **srv_kw) -> "ReplicatedRouter":
        """One replica per device, each with its own copy of `params`
        committed there (dp replication: weights duplicated, nothing
        shared). `devices` defaults to every visible device."""
        from cloud_server_tpu.inference.paged_server import (
            PagedInferenceServer)
        server_cls = server_cls or PagedInferenceServer
        devices = list(devices if devices is not None else jax.devices())
        replicas = []
        for d in devices:
            local = jax.tree.map(lambda x: jax.device_put(x, d), params)
            replicas.append(server_cls(local, cfg, infer_cfg, **srv_kw))
        return cls(replicas)

    # -- placement ----------------------------------------------------------

    def _pick(self, *, count_inflight: bool = False) -> int:
        loads = [r.num_active + r.num_pending + inf
                 for r, inf in zip(self.replicas, self._inflight)]
        k = next(self._rr) % len(self.replicas)
        # least loaded; ties resolve round-robin from k
        i = min(range(len(loads)),
                key=lambda i: (loads[i], (i - k) % len(loads)))
        if count_inflight:
            self._inflight[i] += 1
        return i

    def submit(self, prompt, **kw):
        with self._lock:
            i = self._pick(count_inflight=True)
        try:
            return self.replicas[i].submit(prompt, **kw)
        finally:
            # the request is now in the replica's pending queue (or was
            # rejected) — either way its load is visible/settled again
            with self._lock:
                self._inflight[i] -= 1

    def generate(self, prompts, *, max_new_tokens=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    # -- aggregate surface (HTTP front-end compatible) ----------------------

    def embed(self, prompts):
        """Embeddings via the least-loaded replica (same weights
        everywhere, so any replica's answer is THE answer)."""
        with self._lock:
            i = self._pick()
        fn = getattr(self.replicas[i], "embed", None)
        if fn is None:
            raise ValueError(
                "this serving backend does not support embeddings")
        return fn(prompts)

    @property
    def adapters(self):
        """The adapter registry (replica 0's — add_adapter keeps every
        replica's registry identical, so ids/names agree fleet-wide)."""
        return getattr(self.replicas[0], "adapters", None)

    def add_adapter(self, name: str, lora_params, lora_cfg) -> int:
        """Register a LoRA adapter on EVERY replica (requests routed
        anywhere must find it). Returns the (fleet-wide) adapter id."""
        ids = {r.add_adapter(name, lora_params, lora_cfg)
               for r in self.replicas}
        if len(ids) != 1:  # registries diverged (out-of-band adds)
            raise RuntimeError(
                f"adapter {name!r} got inconsistent ids across "
                f"replicas: {sorted(ids)}; register adapters through "
                "the router only")
        return ids.pop()

    @property
    def num_active(self) -> int:
        return sum(r.num_active for r in self.replicas)

    @property
    def num_pending(self) -> int:
        return sum(r.num_pending for r in self.replicas)

    @property
    def tokens_emitted(self) -> int:
        return sum(r.tokens_emitted for r in self.replicas)

    def metrics_snapshot(self) -> dict:
        """FLEET-wide metrics: every replica's registry snapshot merged
        (histogram buckets add bucket-for-bucket — identical fixed
        ladders by construction — so a dp deployment's /metrics reports
        true fleet percentiles, not replica-0's)."""
        from cloud_server_tpu.utils.serving_metrics import merge_snapshots
        return merge_snapshots(
            r.metrics_snapshot() for r in self.replicas
            if hasattr(r, "metrics_snapshot"))

    def flight_window(self, n: int | None = None) -> list[dict]:
        """Recent flight-recorder records across the fleet, each tagged
        with its replica index, ordered by wall-clock timestamp."""
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "flight_window", None)
            if fn is not None:
                out += [{"replica": i, **rec} for rec in fn(n)]
        out.sort(key=lambda rec: rec.get("ts", 0.0))
        return out

    def step(self) -> int:
        busy = 0
        for r in self.replicas:
            busy += r.step()
        return busy

    def run_until_idle(self) -> None:
        while any(r.num_pending or r.num_active
                  or getattr(r, "_jobs", ())
                  for r in self.replicas):
            self.step()

    def start(self) -> "ReplicatedRouter":
        for r in self.replicas:
            r.start()
        return self

    def stop(self, drain: bool = False,
             timeout: float | None = None) -> None:
        for r in self.replicas:
            try:
                r.stop(drain=drain, timeout=timeout)
            except TypeError:  # replica without drain support
                r.stop()

"""Data-parallel serving scale-OUT: a replica router.

Tensor-parallel serving scales UP inside one mesh
(`PagedInferenceServer(mesh=...)`: params/pools sharded over tp, XLA
collectives on ICI). This module is the other axis: N INDEPENDENT
replicas — each owning a full copy of the weights (on its own device,
submesh, or host) and its own scheduler — behind a single submit().
The router is pure host-side policy; replicas never synchronize with
each other, so throughput and availability scale linearly and a
replica failure sheds only its own in-flight work (the same shape as
the reference stacks' multi-replica deployments: router + N engines,
re-built here without any cross-replica NCCL).

Placement: least-loaded (active + pending), round-robin on ties — the
rotation keeps a cold, empty fleet from piling every request on
replica 0. Tenant-tagged submits (multi-tenant QoS, inference/qos.py)
break ties from the TENANT'S OWN stable home offset instead of the
global rotation: on an un-loaded fleet a tenant's requests land on the
same replica first (radix prefix-cache locality for its prompts) while
load imbalance still dominates the pick the moment it appears. QoS
limits are PER REPLICA (each replica owns an independent registry):
token buckets and max_pending bound a tenant on each replica, so its
fleet-wide ceiling is ~N× the configured value — divide rates by the
replica count when a fleet-wide bound is the intent. Fair-share
weights need no scaling (ratios converge per replica).

The router exposes the submit / num_active / num_pending / start /
stop surface the HTTP front-end expects, so
`HttpFrontend(ReplicatedRouter(...))` serves a fleet unchanged.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(multi-replica serving scale-out).
"""

from __future__ import annotations

import inspect
import itertools
import logging
import queue
import threading
import time
import zlib
from typing import Sequence

import jax

from cloud_server_tpu.inference.request_trace import (any_trace,
                                                      continuation_ctx)
from cloud_server_tpu.inference.server import QueueFullError

_log = logging.getLogger(__name__)

# Per-replica circuit-breaker states. closed = routing normally;
# open = the replica failed `breaker_threshold` times in a row and is
# excluded from placement until `breaker_reset_s` elapses; half_open =
# the reset elapsed and exactly ONE probe submit may route there — its
# outcome decides closed vs re-open.
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                  BREAKER_OPEN: 2}

# Replica roles for disaggregated prefill/decode serving. colocated =
# today's behavior (every replica admits and decodes); prefill = new
# admissions chunk-prefill here, then interactive requests hand off;
# decode = handoff destinations, pinned low-latency decode. A fleet is
# DISAGGREGATED only when it has at least one prefill AND one decode
# replica — any other role mix degrades to colocated placement.
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_COLOCATED = "colocated"
_VALID_ROLES = frozenset({ROLE_PREFILL, ROLE_DECODE, ROLE_COLOCATED})


class _Breaker:
    """One replica's circuit-breaker record (mutated under the
    router's lock only)."""

    __slots__ = ("state", "failures", "opened_at", "probing")

    def __init__(self):
        self.state = BREAKER_CLOSED
        self.failures = 0       # consecutive, reset on any success
        self.opened_at = 0.0    # monotonic moment the breaker opened
        self.probing = False    # half_open: a probe submit is in flight


class _DetachedSlot:
    """Tombstone occupying a removed replica's index. The per-replica
    arrays (`replicas`/`roles`/`_inflight`/`_breakers`/...) are
    indexed by position everywhere — submits capture an index, then
    call into it AFTER the router lock is released — so removal must
    never shift indices. A detached slot is permanently unready and
    empty; a racing submit that captured the index before detachment
    gets a RuntimeError from `submit()` and fails over like any other
    server-class refusal. `add_replica` reuses detached indices, so a
    scale-up/scale-down cycle does not grow the arrays without
    bound."""

    ready = False
    num_active = 0
    num_pending = 0
    tokens_emitted = 0

    def submit(self, prompt, **kw):
        raise RuntimeError("replica detached (removed from the fleet)")

    def step(self) -> int:
        return 0

    def start(self):
        return self

    def stop(self, *a, **kw) -> None:
        pass


class ReplicatedRouter:
    """Route requests across independent serving replicas, with
    per-replica circuit breakers and failover retry.

    Failure handling (the fleet's failure-domain contract):

      * A replica whose submit() raises a server error is skipped and
        the submit FAILS OVER to the next healthy replica; the client
        never sees a single-replica crash as long as any replica
        accepts.
      * A request that fails IN FLIGHT (scheduler crash -> _fail_all,
        stop-before-complete) is offered back to the router by the
        replica's completion path (`Request._fail_handler`). If it
        emitted ZERO tokens — the safe-retry rule: nothing was ever
        streamed, so resubmission cannot duplicate output — and its
        deadline has not passed, the router resubmits it to a healthy
        replica (excluding every replica it already failed on) and the
        original Request handle completes with the retry's outcome;
        its trace gains a `router_retry` span in the same trace tree.
      * A request that already STREAMED tokens is MIGRATED instead
        (inference/migration.py): its host state — generated tokens,
        position-keyed RNG seed, grammar progress, deadline
        remainder — is salvaged from the handle and resumed on a
        healthy replica at the exact next token, on the same stream
        (greedy outputs are token-identical to an uninterrupted run;
        seeded sampling is exact because RNG streams are
        position-keyed). The trace gains a `migrate` span in the same
        tree. Only when migration cannot proceed (export fault, no
        healthy replica, past deadline, non-migratable backend) does
        the old fail-fast contract apply and the HTTP front-end marks
        the failure `"retriable": false`.
      * `drain(replica_index)` evacuates a replica for maintenance:
        every active request live-migrates to a healthy replica
        before the drain waits out whatever could not move — replica
        maintenance is a zero-token-loss operation.
      * Every failure trips the failing replica's breaker: after
        `breaker_threshold` consecutive failures it OPENS (excluded
        from placement), after `breaker_reset_s` it half-opens for one
        probe submit, and a probe success closes it again.

    Breaker state is surfaced on /healthz (`breaker_states()`), and
    the retry/failover/migration/breaker counters ride
    `metrics_snapshot()` with the `cloud_server_router_` families
    (docs/observability.md)."""

    def __init__(self, replicas: Sequence, *,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 30.0,
                 roles: Sequence[str] | None = None):
        if not replicas:
            raise ValueError("need at least one replica")
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if breaker_reset_s <= 0:
            raise ValueError("breaker_reset_s must be > 0")
        self.replicas = list(replicas)
        # disaggregated prefill/decode roles (docs/serving.md): None —
        # the default — means every replica is colocated and every
        # placement/handoff path below short-circuits, byte-identical
        # to the role-less router (pinned by the existing exact-output
        # and dispatch-count guard tests).
        if roles is None:
            self.roles = [ROLE_COLOCATED] * len(self.replicas)
        else:
            self.roles = [str(r) for r in roles]
            if len(self.roles) != len(self.replicas):
                raise ValueError(
                    f"roles has {len(self.roles)} entries for "
                    f"{len(self.replicas)} replicas")
            bad = set(self.roles) - _VALID_ROLES
            if bad:
                raise ValueError(
                    f"unknown replica roles {sorted(bad)}; valid: "
                    f"{sorted(_VALID_ROLES)}")
        self._disagg = (ROLE_PREFILL in self.roles
                        and ROLE_DECODE in self.roles)
        if (any(r != ROLE_COLOCATED for r in self.roles)
                and not self._disagg):
            raise ValueError(
                "a role-specialized fleet needs at least one "
                "'prefill' AND one 'decode' replica (got "
                f"{self.roles}); use all-'colocated' (or roles=None) "
                "for a uniform fleet")
        self._rr = itertools.count()
        self._lock = threading.Lock()
        # submits picked but not yet visible in their replica's pending
        # queue: _pick() counts them so concurrent submitters see fresh
        # load instead of racing into the same replica (the lock is NOT
        # held across the replica's submit() — that can block on model
        # work — so the counter is what bridges the window)
        self._inflight = [0] * len(self.replicas)
        # indices whose replica was removed at runtime (remove_replica):
        # tombstoned, never picked, reusable by add_replica; _removing
        # marks an in-progress removal so two removers cannot claim
        # one slot
        self._detached: set[int] = set()
        self._removing: set[int] = set()
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self._breakers = [_Breaker() for _ in self.replicas]
        # router-level metrics: the router owns fleet plumbing no
        # replica can see (failovers, retries, breaker trips), so it
        # keeps its own registry and merges it into metrics_snapshot()
        from cloud_server_tpu.utils.serving_metrics import MetricsRegistry
        reg = self._registry = MetricsRegistry()
        self._m_failovers = reg.counter(
            "router_submit_failovers_total",
            "submit() calls re-routed after a replica refused with a "
            "server error")
        self._m_retries = reg.counter(
            "router_retries_total",
            "In-flight requests resubmitted to another replica after "
            "failing with zero tokens emitted")
        self._m_retry_success = reg.counter(
            "router_retry_success_total",
            "Failover retries whose resubmission completed normally")
        self._m_migrations = reg.counter(
            "router_migrations_total",
            "Mid-stream failures and drain evacuations handed to "
            "live migration (state salvaged, resumption dispatched)")
        self._m_migration_success = reg.counter(
            "router_migration_success_total",
            "Live migrations whose resumed request completed "
            "normally on the destination replica")
        self._migration_ms = reg.histogram(
            "migration_ms",
            "Live-migration handoff latency (failure or drain offer "
            "through destination re-admission), ms",
            buckets=(1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                     500.0, 1000.0, 2500.0, 5000.0))
        self._m_breaker_open = reg.counter(
            "router_breaker_open_total",
            "Circuit-breaker open transitions (closed/half_open -> "
            "open), fleet lifetime")
        self._m_drainless = reg.counter(
            "router_drainless_stops_total",
            "stop(drain=...) calls that fell back to a drain-less "
            "replica stop() (replica without drain support)")
        # disaggregation handoff counters (zeros unless a role-
        # specialized fleet runs): attempts, continuations admitted on
        # a decode replica, and the admission-to-admission latency.
        # Registered EAGERLY so the families exist for the docs drift
        # check whether or not a handoff ever runs.
        self._m_handoffs = reg.counter(
            "router_handoffs_total",
            "Disaggregation handoffs attempted (prefill-complete "
            "requests offered to a decode replica)")
        self._m_handoff_success = reg.counter(
            "router_handoff_success_total",
            "Disaggregation handoffs whose continuation was admitted "
            "on a decode replica")
        self._handoff_ms = reg.histogram(
            "router_handoff_ms",
            "Disaggregation handoff latency (prefill completion "
            "through destination re-admission), ms",
            buckets=(1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                     500.0, 1000.0, 2500.0, 5000.0))
        for i in range(len(self.replicas)):
            reg.gauge("router_breaker_state",
                      "Per-replica breaker state (0 closed, 1 "
                      "half_open, 2 open)",
                      labels={"replica": str(i)})
            # the fleet's role map as a labeled constant gauge, so
            # per-role splits of any replica-tagged series are
            # readable from one scrape
            reg.gauge("router_replica_role",
                      "Replica role assignment (constant 1; the role "
                      "rides the labels)",
                      labels={"replica": str(i),
                              "role": self.roles[i]}).set(1)
        reg.add_collector(self._collect_router_metrics)
        # can each replica's submit() carry the failover hook?
        # (our servers take `fail_handler=`; third-party backends
        # without it — or without **kwargs — keep the old no-failover
        # behavior instead of TypeError-ing every submit)
        self._accepts_hook = [self._submit_takes_hook(r)
                              for r in self.replicas]
        self._accepts_handoff = [self._submit_takes_hook(r, "handoff")
                                 for r in self.replicas]
        # disaggregation handoff plumbing: a prefill replica fires the
        # submit-time handoff callback (outside its step lock) when a
        # request's chunked prefill completes; the callback enqueues
        # here and ONE daemon worker migrates request-by-request — a
        # flood of simultaneous completions must not mint a thread
        # each. Colocated fleets never start the worker.
        self._handoff_q: "queue.SimpleQueue | None" = None
        self._handoff_thread: threading.Thread | None = None
        if self._disagg:
            self._handoff_q = queue.SimpleQueue()
            self._handoff_thread = threading.Thread(
                target=self._handoff_worker, daemon=True,
                name="router-handoff")
            self._handoff_thread.start()

    @staticmethod
    def _submit_takes_hook(replica, kwarg: str = "fail_handler") -> bool:
        try:
            params = inspect.signature(replica.submit).parameters
        except (TypeError, ValueError):
            return False
        return (kwarg in params
                or any(p.kind == p.VAR_KEYWORD
                       for p in params.values()))

    @classmethod
    def over_devices(cls, params, cfg, infer_cfg, *, devices=None,
                     server_cls=None, **srv_kw) -> "ReplicatedRouter":
        """One replica per device, each with its own copy of `params`
        committed there (dp replication: weights duplicated, nothing
        shared). `devices` defaults to every visible device."""
        from cloud_server_tpu.inference.paged_server import (
            PagedInferenceServer)
        server_cls = server_cls or PagedInferenceServer
        devices = list(devices if devices is not None else jax.devices())
        replicas = []
        for d in devices:
            local = jax.tree.map(lambda x: jax.device_put(x, d), params)
            replicas.append(server_cls(local, cfg, infer_cfg, **srv_kw))
        return cls(replicas)

    # -- placement ----------------------------------------------------------

    def _breaker_admits_locked(self, i: int, now: float) -> bool:
        """May placement route to replica `i` right now? (caller holds
        the router lock). Lazily transitions open -> half_open when
        the reset window elapsed; half_open admits only while no probe
        is in flight."""
        b = self._breakers[i]
        if b.state == BREAKER_CLOSED:
            return True
        if b.state == BREAKER_OPEN:
            if now - b.opened_at < self.breaker_reset_s:
                return False
            b.state = BREAKER_HALF_OPEN
            b.probing = False
        return not b.probing

    @staticmethod
    def _prefill_load(replica) -> int:
        """Placement load for a PREFILL pick: queued prompt work, not
        occupied decode slots. Our servers expose the exact figure
        (pending prefill tokens); a backend without it degrades to the
        generic request count."""
        n = getattr(replica, "pending_prefill_tokens", None)
        return (replica.num_active + replica.num_pending
                if n is None else int(n))

    def _role_candidates(self, cands: list[int],
                         role: str | None) -> list[int]:
        """Narrow a candidate set to replicas of `role` — but NEVER to
        empty: when no replica of the wanted role is healthy (open
        breakers, drains), placement falls back to whatever is, so a
        role-specialized fleet degrades to colocated behavior instead
        of refusing work."""
        if role is None or not self._disagg:
            return cands
        pref = [j for j in cands if self.roles[j] == role]
        return pref or cands

    def _plan_roles(self, tenant: str | None) -> tuple[str | None, bool]:
        """The disaggregation placement plan for one submit:
        (admission role preference, arm the prefill->decode handoff?).
        Every request ADMITS toward prefill capacity (admission cost
        IS prefill); only interactive-class tenants hand off to a
        decode replica afterward — batch/best_effort decode where
        they prefilled, soaking prefill-replica slack instead of
        polluting the low-latency decode pool."""
        # analysis: allow[lock-discipline] GIL-atomic bool: topology
        # flips only inside add/remove_replica under _lock; a stale
        # read routes one request with the old topology, which the
        # failover/handoff paths tolerate by design
        if not self._disagg:
            return None, False
        cls = "interactive"
        try:
            q = self.qos
            if q is not None:
                cls = q.priority_class(q.resolve(tenant))
        except Exception:  # noqa: BLE001 — unknown tenant/backends
            pass
        return ROLE_PREFILL, cls == "interactive"

    def _pick(self, *, tenant: str | None = None,
              count_inflight: bool = False,
              exclude: frozenset | set = frozenset(),
              strict: bool = False,
              role: str | None = None) -> int | None:
        n = len(self.replicas)
        if role == ROLE_PREFILL and self._disagg:
            # prefill picks balance by queued PROMPT tokens: decode
            # occupancy (num_active) says nothing about how long a new
            # prompt waits for chunk-prefill budget
            loads = [self._prefill_load(r) + inf
                     for r, inf in zip(self.replicas, self._inflight)]
        else:
            loads = [r.num_active + r.num_pending + inf
                     for r, inf in zip(self.replicas, self._inflight)]
        if tenant is None:
            k = next(self._rr) % n
        else:
            # tenant-affinity tie-break: a stable per-tenant home
            # offset (crc32, not hash() — PYTHONHASHSEED-independent)
            # so an idle fleet serves a tenant from one replica (its
            # prompts hit that replica's radix prefix cache) while
            # least-loaded still wins under any load skew
            k = zlib.crc32(tenant.encode()) % n
        # readiness- and breaker-aware placement: a draining (or
        # stopped) replica advertises ready=False, an open breaker
        # excludes a repeatedly-failing one, and `exclude` carries a
        # failover's already-failed set. Fallback chain (non-strict):
        # healthy -> merely ready -> anything not excluded -> all, so
        # a wholly-unready fleet surfaces the replica's own refusal
        # instead of an index error. Strict mode (failover retries)
        # returns None rather than re-picking an excluded replica —
        # resubmitting to the replica that just failed the request
        # would retry into the same failure.
        now = time.monotonic()
        # a detached (removed) slot is out of EVERY tier, including
        # the last-resort fallback: there is no replica behind it
        alive = [j for j in range(n) if j not in self._detached]
        ready = [j for j in alive
                 if j not in exclude
                 and getattr(self.replicas[j], "ready", True)]
        cands = ([j for j in ready
                  if self._breaker_admits_locked(j, now)] or ready)
        if not cands:
            if strict:
                return None
            cands = ([j for j in alive if j not in exclude] or alive)
        # role preference narrows AFTER health (a healthy off-role
        # replica beats a broken on-role one — see _role_candidates)
        cands = self._role_candidates(cands, role)
        # least loaded; ties resolve round-robin from k
        i = min(cands, key=lambda j: (loads[j], (j - k) % n))
        b = self._breakers[i]
        if b.state == BREAKER_HALF_OPEN and count_inflight:
            # this pick is the probe (submit paths only — monitoring
            # picks like embed() never resolve a probe, so they must
            # not claim one)
            b.probing = True
        if count_inflight:
            self._inflight[i] += 1
        return i

    def _release_probe(self, i: int) -> None:
        """A probe submit resolved WITHOUT a breaker verdict (client-
        class refusal: queue full, bad request): free the half-open
        slot so the next submit can probe — otherwise the breaker
        wedges with `probing` latched forever."""
        with self._lock:
            b = self._breakers[i]
            if b.state == BREAKER_HALF_OPEN:
                b.probing = False

    def _record_breaker_failure(self, i: int) -> None:
        """One failure event on replica `i` (submit refusal or an
        in-flight request failure): consecutive count up; at the
        threshold — or on a failed half-open probe — the breaker
        OPENS and placement stops routing there until the reset."""
        with self._lock:
            b = self._breakers[i]
            b.failures += 1
            if b.state == BREAKER_HALF_OPEN or (
                    b.state == BREAKER_CLOSED
                    and b.failures >= self.breaker_threshold):
                b.state = BREAKER_OPEN
                b.opened_at = time.monotonic()
                b.probing = False
                self._m_breaker_open.inc()

    def _record_breaker_success(self, i: int) -> None:
        with self._lock:
            b = self._breakers[i]
            b.failures = 0
            b.state = BREAKER_CLOSED
            b.probing = False

    def _make_fail_hook(self, replica: int, prompt, kw: dict,
                        excluded: frozenset, orig):
        """The Request._fail_handler a submit carries INTO the
        replica: context rides in the closure (no post-submit
        attribute installation — a scheduler crash in that window
        would otherwise complete the request past the hook). `orig`
        is None on the first hop (the failing request IS the
        original client handle)."""
        def hook(req) -> bool:
            return self._on_request_failed(
                req, replica, prompt, kw, excluded,
                orig if orig is not None else req)
        return hook

    def submit(self, prompt, **kw):
        t0 = time.perf_counter()
        excluded: set[int] = set()
        role, arm_handoff = self._plan_roles(kw.get("tenant"))
        while True:
            with self._lock:
                i = self._pick(tenant=kw.get("tenant"),
                               count_inflight=True, exclude=excluded,
                               role=role)
            # analysis: allow[lock-discipline] GIL-atomic list index:
            # capability slots are written once at attach under _lock
            # and i came from _pick — a read racing an attach at worst
            # skips the hook for that one request
            hkw = ({"fail_handler": self._make_fail_hook(
                        i, prompt, dict(kw), frozenset(excluded),
                        None)}
                   if self._accepts_hook[i] else {})
            if (arm_handoff and self.roles[i] == ROLE_PREFILL
                    and self._accepts_handoff[i]  # analysis: allow[lock-discipline] GIL-atomic capability slot, see hkw above
                    and hasattr(self.replicas[i], "migrate_export")):
                # prefill landed on a prefill replica: ride the
                # handoff hook IN through submit (same no-install-
                # window rule as the failover hook) so the replica
                # pings us the moment chunked prefill completes
                hkw["handoff"] = self._make_handoff_hook(i, dict(kw))
            try:
                req = self.replicas[i].submit(prompt, **hkw, **kw)
            except QueueFullError:
                # backpressure (global bound, tenant 429, brownout
                # shed): a CLIENT-class refusal, not a replica
                # failure — no breaker event, no failover (the 429's
                # Retry-After is the contract)
                with self._lock:
                    self._inflight[i] -= 1
                self._release_probe(i)
                raise
            except RuntimeError as exc:
                # server-class refusal (stopped, crashed, injected):
                # trip the breaker — unless the replica is merely
                # unready (draining), which is expected — and FAIL
                # OVER to the next replica
                with self._lock:
                    self._inflight[i] -= 1
                if getattr(self.replicas[i], "ready", True):
                    self._record_breaker_failure(i)
                else:
                    self._release_probe(i)
                excluded.add(i)
                if len(excluded) >= len(self.replicas):
                    raise
                self._m_failovers.inc()
                continue
            except BaseException:
                with self._lock:
                    self._inflight[i] -= 1
                self._release_probe(i)
                raise
            self._record_breaker_success(i)
            tr = getattr(req, "trace", None)
            if tr is not None:
                # the fleet half of the request's ONE span tree: the
                # routing decision as an explicit span (pick through
                # replica-submit return) + the replica tag every
                # replica-side span inherits via the root
                tr.annotate(replica=i)
                tr.add_span("router_pick", t0, time.perf_counter(),
                            replica=i)
            # the request is now in the replica's pending queue — its
            # load is visible/settled again (its failover hook rode
            # IN through submit, so there is no install window a
            # crash could slip past)
            with self._lock:
                self._inflight[i] -= 1
            return req

    # -- failover retry ------------------------------------------------------

    def _on_request_failed(self, req, replica: int, prompt, kw: dict,
                           excluded: frozenset, orig) -> bool:
        """Body of the closure _make_fail_hook plants as
        Request._fail_handler: a router-submitted request completed
        with an "error:" finish_reason on its replica. Runs on the
        FAILING replica's thread (possibly inside _fail_all, holding
        its step lock), so this only classifies and hands off; the
        resubmission happens on a fresh daemon thread. True = the
        router took ownership and a retry will complete the request;
        False = the failure stands (the replica unblocks waiters)."""
        if getattr(req, "_request_fault", False):
            # REQUEST-caused error (e.g. it can never fit the page
            # pool): it would fail identically on every replica — no
            # retry, and no breaker event against a healthy replica
            return False
        self._record_breaker_failure(replica)
        excluded = set(excluded) | {replica}
        # the SAFE-RETRY rule, upgraded by live migration: a request
        # that streamed NOTHING resubmits plainly (at-most-once token
        # delivery — nothing to duplicate); one that already streamed
        # is MIGRATED — host state salvaged from the handle, resumed
        # on a healthy replica at the exact next token. Only when the
        # migration cannot even start (checks below, export fault,
        # non-migratable backend) does the failure stand and the HTTP
        # layer mark it retriable: false.
        mid_stream = bool(req.tokens or orig.tokens)
        if orig._cancel.is_set():
            return False
        if (orig.deadline is not None
                and time.perf_counter() > orig.deadline):
            return False  # past deadline: retrying cannot help
        if len(excluded) >= len(self.replicas):
            return False
        with self._lock:
            now = time.monotonic()
            if not any(j not in excluded
                       and getattr(r, "ready", True)
                       and self._breaker_admits_locked(j, now)
                       for j, r in enumerate(self.replicas)):
                return False  # nowhere healthy to retry
        if mid_stream:
            salvage = getattr(self.replicas[replica],
                              "migrate_salvage", None)
            if salvage is None:
                return False  # backend without migration: fail fast
            # whichever handle carries MORE of the stream is the
            # truth (req is the failing hop's request — on hop > 1 it
            # holds the full pre-filled stream; orig only mirrors at
            # success)
            src = req if len(req.tokens) >= len(orig.tokens) else orig
            try:
                snap = salvage(src, reason="failover")
            except Exception:  # noqa: BLE001 — injected or real
                return False  # export failed: the old contract stands
            self._m_migrations.inc()
            threading.Thread(
                target=self._migrate_submit,
                args=(orig, snap, replica, excluded, kw),
                daemon=True, name="router-migrate").start()
            return True
        self._m_retries.inc()
        threading.Thread(
            target=self._retry_submit,
            args=(orig, replica, excluded, prompt, kw),
            daemon=True, name="router-retry").start()
        return True

    def _retry_submit(self, orig, from_replica: int, excluded: set,
                      prompt, kw) -> None:
        """Resubmit a zero-token failed request to a healthy replica
        (retry worker thread). The ORIGINAL Request stays the client's
        handle: the retry submits with the same stream callback,
        sampling, and tenant, joins the same trace (gaining a
        `router_retry` span), and on completion mirrors its outcome
        onto the original before unblocking its waiters."""
        t_fail = time.perf_counter()
        kw = dict(kw)
        if orig.deadline is not None:
            remaining = orig.deadline - time.perf_counter()
            if remaining <= 0:
                orig._done.set()  # expired while handing off
                return
            kw["deadline_s"] = remaining
        ctx = continuation_ctx(orig)
        if ctx is not None:
            # the retry joins the ORIGINAL trace (same trace id,
            # parented at the original root), so the hop is one story
            # — tail-provisional traces too, with sampled=False so
            # the continuation stays on the tail-retention path
            kw["trace_ctx"] = ctx
        while True:
            with self._lock:
                i = self._pick(tenant=kw.get("tenant"),
                               count_inflight=True, exclude=excluded,
                               strict=True)
            if i is None:
                break  # nothing healthy left: the failure stands
            # analysis: allow[lock-discipline] GIL-atomic capability
            # slot (written once at attach under _lock), as in submit
            hkw = ({"fail_handler": self._make_fail_hook(
                        i, prompt, dict(kw), frozenset(excluded),
                        orig)}
                   if self._accepts_hook[i] else {})
            try:
                new = self.replicas[i].submit(prompt, **hkw, **kw)
            except Exception as exc:  # noqa: BLE001 — any refusal: next
                with self._lock:
                    self._inflight[i] -= 1
                if (isinstance(exc, RuntimeError)
                        and not isinstance(exc, QueueFullError)
                        and getattr(self.replicas[i], "ready", True)):
                    self._record_breaker_failure(i)
                else:
                    self._release_probe(i)
                excluded.add(i)
                if len(excluded) >= len(self.replicas):
                    break
                continue
            with self._lock:
                self._inflight[i] -= 1
            self._record_breaker_success(i)
            if not hasattr(new, "_fail_handler"):
                # a backend without the Request completion surface
                # cannot report the retry's outcome back — the
                # original failure stands (and the resubmitted work,
                # if any, runs unobserved)
                orig._done.set()
                return
            # error completions already route through the fail hook
            # that rode IN with the submit; _on_done handles success
            # mirroring. The only window left is a NORMAL completion
            # before _on_done lands — closed by the idempotent
            # re-check below.
            new._router_orig = orig
            new._on_done = self._mirror_retry
            # cancel propagation: cancelling the original handle now
            # cancels the retry (the original's own replica is gone).
            # GENERATION-guarded under the router lock: a slow hop-N
            # thread must not overwrite the link a later hop already
            # installed — cancel() would then hit the dead earlier
            # retry while the live one decodes on, orphaned. The
            # excluded set grows strictly per hop, so its size is the
            # hop's generation.
            with self._lock:
                gen = len(excluded)
                if gen >= getattr(orig, "_router_cancel_gen", -1):
                    orig._router_cancel_gen = gen
                    orig._on_cancel = lambda _r, _n=new: _n.cancel()
            if orig._cancel.is_set():
                new.cancel()
            tr = any_trace(new)
            if tr is not None:
                tr.annotate(replica=i, retry_of=orig.request_id)
                tr.add_span("router_retry", t_fail,
                            time.perf_counter(),
                            from_replica=from_replica, replica=i,
                            attempt=len(excluded))
            if new.done:
                self._mirror_retry(new)
            return
        # could not resubmit anywhere: the original failure stands
        orig._done.set()

    def _migrate_submit(self, orig, snap, from_replica: int,
                        excluded: set, kw) -> None:
        """Resume a salvaged mid-stream request on a healthy replica
        (migration worker thread; `_retry_submit`'s shape, but the
        re-admission goes through `migrate_import` so the destination
        resumes at the exact next token). The ORIGINAL Request stays
        the client's handle: the continuation emits only NEW tokens
        through the same stream callback, joins the same trace
        (gaining a `migrate` span), and on completion mirrors its
        outcome onto the original before unblocking its waiters."""
        t_fail = time.perf_counter()
        deadline_s = None
        if orig.deadline is not None:
            remaining = orig.deadline - time.perf_counter()
            if remaining <= 0:
                orig._done.set()  # expired while handing off
                return
            deadline_s = remaining
        trace_ctx = continuation_ctx(orig)
        while True:
            with self._lock:
                i = self._pick(tenant=kw.get("tenant"),
                               count_inflight=True, exclude=excluded,
                               strict=True)
            if i is None:
                break  # nothing healthy left: the failure stands
            imp = getattr(self.replicas[i], "migrate_import", None)
            if imp is None:
                # non-migratable backend: skip it for THIS request
                # without a breaker event (it did nothing wrong)
                with self._lock:
                    self._inflight[i] -= 1
                self._release_probe(i)
                excluded.add(i)
                if len(excluded) >= len(self.replicas):
                    break
                continue
            # analysis: allow[lock-discipline] GIL-atomic capability
            # slot (written once at attach under _lock), as in submit
            hook = (self._make_fail_hook(
                        i, list(snap.prompt), dict(kw),
                        frozenset(excluded), orig)
                    if self._accepts_hook[i] else None)
            try:
                new = imp(snap, stream=kw.get("stream"),
                          fail_handler=hook, trace_ctx=trace_ctx,
                          deadline_s=deadline_s)
            except Exception as exc:  # noqa: BLE001 — any refusal: next
                with self._lock:
                    self._inflight[i] -= 1
                if (isinstance(exc, RuntimeError)
                        and not isinstance(exc, QueueFullError)
                        and getattr(self.replicas[i], "ready", True)):
                    self._record_breaker_failure(i)
                else:
                    self._release_probe(i)
                excluded.add(i)
                if len(excluded) >= len(self.replicas):
                    break
                continue
            with self._lock:
                self._inflight[i] -= 1
            self._record_breaker_success(i)
            # same mirroring/cancel-chain contract as _retry_submit
            # (see the comments there); _router_migrated routes the
            # success onto the migration counter instead of retry's
            new._router_orig = orig
            new._router_migrated = True
            new._on_done = self._mirror_retry
            with self._lock:
                gen = len(excluded)
                if gen >= getattr(orig, "_router_cancel_gen", -1):
                    orig._router_cancel_gen = gen
                    orig._on_cancel = lambda _r, _n=new: _n.cancel()
            if orig._cancel.is_set():
                new.cancel()
            tr = any_trace(new)
            if tr is not None:
                tr.annotate(replica=i, migrate_of=orig.request_id)
                tr.add_span("migrate", t_fail, time.perf_counter(),
                            from_replica=from_replica, replica=i,
                            attempt=len(excluded),
                            reason=snap.reason,
                            tokens_salvaged=len(snap.tokens),
                            kv_pages=snap.n_kv_pages())
            self._migration_ms.observe(
                (time.perf_counter() - t_fail) * 1e3)
            if new.done:
                self._mirror_retry(new)
            return
        # could not resume anywhere: the original failure stands
        orig._done.set()

    # -- disaggregation handoff ---------------------------------------------

    def _make_handoff_hook(self, replica: int, kw: dict):
        """The submit-time handoff callback a prefill replica fires
        (outside its step lock) the moment a request's chunked
        prefill completes and its first token streams. The hook only
        ENQUEUES — the scheduler thread must never block on another
        replica's admission path."""
        def hook(req) -> None:
            # analysis: allow[lock-discipline] GIL-atomic reference
            # snapshot: the queue is created once on the disagg
            # transition under _lock and never replaced
            q = self._handoff_q
            if q is not None:
                q.put((req, replica, kw))
        return hook

    def _handoff_worker(self) -> None:
        """Daemon loop draining the handoff queue one request at a
        time. A handoff is an OPTIMIZATION: any exception leaves the
        request decoding where it prefilled (or, after a successful
        export, the loop inside _handoff_one owns re-admission)."""
        # analysis: allow[lock-discipline] GIL-atomic reference
        # snapshot: the worker thread starts under _lock strictly
        # after the queue exists, and the queue is never replaced
        q = self._handoff_q
        while True:
            item = q.get()
            if item is None:
                return  # stop() sentinel
            try:
                self._handoff_one(*item)
            except Exception:  # noqa: BLE001 — keep draining
                pass

    def _handoff_one(self, orig, src_i: int, kw: dict) -> None:
        """Move one prefill-complete request to a decode replica:
        export the committed KV + host state from the prefill replica
        (the final-chunk device->host copies were already started by
        the scheduler's handoff prefetch, so the export's sanctioned
        sync mostly finds them resident) and re-admit through
        `migrate_import`. Until the export commits, the request keeps
        decoding on the prefill replica — a missing/unhealthy decode
        pool costs nothing. AFTER the export the request has left the
        source, so the import loop must land it somewhere: decode
        replicas first, any healthy replica next, the source itself
        last (its pages are still hot in the local prefix cache)."""
        if orig.done or orig._cancel.is_set():
            return
        excluded: set[int] = {src_i}
        with self._lock:
            now = time.monotonic()
            has_dest = any(
                j != src_i and self.roles[j] == ROLE_DECODE
                and getattr(r, "ready", True)
                and self._breaker_admits_locked(j, now)
                and getattr(r, "migrate_import", None) is not None
                for j, r in enumerate(self.replicas))
        if not has_dest:
            return  # no decode capacity: decode where it prefilled
        t0 = time.perf_counter()
        try:
            snap = self.replicas[src_i].migrate_export(
                orig, reason="handoff")
        except Exception:  # noqa: BLE001 — finished/cancelled/mid-
            return  # admission: the request stays local, no handoff
        self._m_handoffs.inc()
        deadline_s = None
        if orig.deadline is not None:
            remaining = orig.deadline - time.perf_counter()
            if remaining <= 0:
                orig.finish_reason = "error:deadline"
                orig._done.set()
                return
            deadline_s = remaining
        trace_ctx = continuation_ctx(orig)
        last_resort = False
        while True:
            with self._lock:
                i = self._pick(tenant=kw.get("tenant"),
                               count_inflight=True, exclude=excluded,
                               strict=True, role=ROLE_DECODE)
            if i is None:
                # nothing else healthy: land it back where it came
                # from before giving up entirely
                if last_resort:
                    break
                last_resort = True
                with self._lock:
                    self._inflight[src_i] += 1
                i = src_i
            imp = getattr(self.replicas[i], "migrate_import", None)
            if imp is None:
                with self._lock:
                    self._inflight[i] -= 1
                self._release_probe(i)
                excluded.add(i)
                continue
            # analysis: allow[lock-discipline] GIL-atomic capability
            # slot (written once at attach under _lock), as in submit
            hook = (self._make_fail_hook(
                        i, list(snap.prompt), dict(kw),
                        frozenset(excluded), orig)
                    if self._accepts_hook[i] else None)
            try:
                new = imp(snap, stream=kw.get("stream"),
                          fail_handler=hook, trace_ctx=trace_ctx,
                          deadline_s=deadline_s)
            except Exception as exc:  # noqa: BLE001 — any refusal: next
                with self._lock:
                    self._inflight[i] -= 1
                if (isinstance(exc, RuntimeError)
                        and not isinstance(exc, QueueFullError)
                        and getattr(self.replicas[i], "ready", True)):
                    self._record_breaker_failure(i)
                else:
                    self._release_probe(i)
                excluded.add(i)
                continue
            with self._lock:
                self._inflight[i] -= 1
            self._record_breaker_success(i)
            # same mirroring/cancel-chain contract as _migrate_submit;
            # _router_handoff keeps the completion off the failover-
            # migration success counter (handoff success is counted
            # HERE, at admission — the handoff "won" the moment the
            # continuation is decoding on the destination)
            new._router_orig = orig
            new._router_migrated = True
            new._router_handoff = True
            new._on_done = self._mirror_retry
            with self._lock:
                gen = len(excluded)
                if gen >= getattr(orig, "_router_cancel_gen", -1):
                    orig._router_cancel_gen = gen
                    orig._on_cancel = lambda _r, _n=new: _n.cancel()
            if orig._cancel.is_set():
                new.cancel()
            tr = any_trace(new)
            if tr is not None:
                tr.annotate(replica=i, handoff_of=orig.request_id)
                tr.add_span("handoff", t0, time.perf_counter(),
                            from_replica=src_i, replica=i,
                            tokens_salvaged=len(snap.tokens),
                            kv_pages=snap.n_kv_pages())
            if i != src_i:
                self._m_handoff_success.inc()
            self._handoff_ms.observe(
                (time.perf_counter() - t0) * 1e3)
            if new.done:
                self._mirror_retry(new)
            return
        # exported but nowhere to land (source included): the request
        # cannot continue — fail the handle so waiters unblock
        orig.finish_reason = orig.finish_reason or "error:handoff"
        orig._done.set()

    def _mirror_retry(self, new) -> None:
        """Request._on_done of a retry: copy the outcome onto the
        original handle and unblock its waiters (tokens already
        streamed through the shared stream callback). Idempotent
        UNDER THE ROUTER LOCK — both the replica's _on_done callback
        and the retry thread's done re-check may race here, and the
        success counter must move exactly once."""
        orig = getattr(new, "_router_orig", None)
        if orig is None:
            return
        with self._lock:
            if getattr(orig, "_router_mirrored", False):
                return
            orig._router_mirrored = True
        orig.tokens = new.tokens
        orig.logprobs = new.logprobs
        orig.emit_times = new.emit_times
        orig.finish_reason = new.finish_reason
        if (new.finish_reason is not None
                and not new.finish_reason.startswith("error")):
            if getattr(new, "_router_handoff", False):
                # disaggregation handoff: success already counted at
                # import admission (router_handoff_success_total) —
                # this completion is not a failover migration
                pass
            elif getattr(new, "_router_migrated", False):
                self._m_migration_success.inc()
            else:
                self._m_retry_success.inc()
        orig._done.set()
        # `orig` may ITSELF be a router continuation holding the true
        # client handle (a handed-off request drained or failed over
        # again chains through the replica's request object) —
        # propagate so the original submit's waiters unblock too.
        # Idempotency per link bounds the recursion.
        self._mirror_retry(orig)

    def generate(self, prompts, *, max_new_tokens=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    # -- aggregate surface (HTTP front-end compatible) ----------------------

    def embed(self, prompts):
        """Embeddings via the least-loaded replica (same weights
        everywhere, so any replica's answer is THE answer)."""
        with self._lock:
            i = self._pick()
        fn = getattr(self.replicas[i], "embed", None)
        if fn is None:
            raise ValueError(
                "this serving backend does not support embeddings")
        return fn(prompts)

    @property
    def adapters(self):
        """The adapter registry (replica 0's — add_adapter keeps every
        replica's registry identical, so ids/names agree fleet-wide)."""
        return getattr(self.replicas[0], "adapters", None)

    def add_adapter(self, name: str, lora_params, lora_cfg) -> int:
        """Register a LoRA adapter on EVERY replica (requests routed
        anywhere must find it). Returns the (fleet-wide) adapter id."""
        ids = {r.add_adapter(name, lora_params, lora_cfg)
               for r in self.replicas}
        if len(ids) != 1:  # registries diverged (out-of-band adds)
            raise RuntimeError(
                f"adapter {name!r} got inconsistent ids across "
                f"replicas: {sorted(ids)}; register adapters through "
                "the router only")
        return ids.pop()

    @property
    def num_active(self) -> int:
        return sum(r.num_active for r in self.replicas)

    @property
    def num_pending(self) -> int:
        return sum(r.num_pending for r in self.replicas)

    @property
    def ready(self) -> bool:
        """Fleet readiness: True while ANY replica accepts new work
        (a draining replica only removes itself from placement)."""
        return any(getattr(r, "ready", True) for r in self.replicas)

    def breaker_states(self) -> list[dict]:
        """Per-replica breaker view (the /healthz `replicas` block):
        state, consecutive failures, and the replica's own readiness.
        Reading surfaces any lazy open -> half_open transition, so
        the report never shows an open breaker whose reset already
        elapsed."""
        with self._lock:
            now = time.monotonic()
            out = []
            for i, b in enumerate(self._breakers):
                if i in self._detached:
                    continue
                self._breaker_admits_locked(i, now)
                out.append({
                    "replica": i, "role": self.roles[i],
                    "state": b.state,
                    "consecutive_failures": b.failures,
                    "ready": bool(getattr(self.replicas[i], "ready",
                                          True))})
            return out

    def replica_roles(self) -> list[str]:
        """The fleet's role map, by replica index (all "colocated"
        unless the constructor configured a disaggregated fleet)."""
        return list(self.roles)

    def _collect_router_metrics(self) -> None:
        """Scrape-path mirror of breaker state into the router's own
        registry (labeled per replica — a bounded set)."""
        for st in self.breaker_states():
            self._registry.gauge(
                "router_breaker_state",
                "Per-replica breaker state (0 closed, 1 half_open, "
                "2 open)",
                labels={"replica": str(st["replica"])}).set(
                    _BREAKER_GAUGE[st["state"]])

    @property
    def tokens_emitted(self) -> int:
        return sum(r.tokens_emitted for r in self.replicas)

    def metrics_snapshot(self) -> dict:
        """FLEET-wide metrics: every replica's registry snapshot merged
        (histogram buckets add bucket-for-bucket — identical fixed
        ladders by construction — so a dp deployment's /metrics reports
        true fleet percentiles, not replica-0's). The additive gauge
        merge is wrong for RATIO gauges: `tenant_fair_share` (1.0 =
        exactly fair) would read ~N for N fair replicas, so it is
        recomputed from the fleet-merged generated totals
        (tenant_stats), the same rule that function documents.

        The iteration-phase histograms (`iter_phase_ms`, labeled by
        phase) merge bucket-for-bucket like every other histogram —
        identical ms ladders by construction — and the derived
        `host_gap_frac` is deliberately NOT a registered gauge: the
        /stats summary recomputes it from the merged phase sums
        (iteration_profile.profile_summary), so the ratio can never
        be added across replicas by accident."""
        from cloud_server_tpu.utils.serving_metrics import merge_snapshots
        merged = merge_snapshots(
            [r.metrics_snapshot() for r in self.replicas
             if hasattr(r, "metrics_snapshot")]
            # + the router's own families (failover/retry/breaker
            # counters and per-replica breaker-state gauges): fleet
            # plumbing no replica can observe
            + [self._registry.snapshot()])
        tstats = self.tenant_stats()
        for key, entry in merged.items():
            if not key.startswith("cloud_server_tenant_fair_share{"):
                continue
            t = (entry.get("labels") or {}).get("tenant")
            if t in tstats:
                entry["value"] = tstats[t]["fair_share"]
        # spec_accept_rate is a RATIO gauge too: recompute from the
        # fleet-merged drafted/accepted totals, never by adding the
        # per-replica rates
        if "cloud_server_spec_accept_rate" in merged:
            sstats = self.speculation_stats()
            merged["cloud_server_spec_accept_rate"]["value"] = (
                sstats.get("accept_rate", 0.0))
        # same rule for the SLO ratio gauges: attainment/burn recompute
        # from the fleet-merged good/total counts, never by adding the
        # per-replica ratios (two 0.99-attaining replicas must read
        # 0.99, not 1.98)
        srep = self.slo_report()
        if srep is not None:
            for key, entry in merged.items():
                if not (key.startswith("cloud_server_slo_attainment{")
                        or key.startswith("cloud_server_slo_burn_rate{")):
                    continue
                lbl = entry.get("labels") or {}
                went = (srep["classes"]
                        .get(lbl.get("class"), {})
                        .get("metrics", {})
                        .get(lbl.get("metric"), {})
                        .get("windows", {})
                        .get(lbl.get("window_s")))
                if went is None:
                    continue
                if "attainment{" in key:
                    att = went["attainment"]
                    entry["value"] = 1.0 if att is None else att
                else:
                    entry["value"] = went["burn_rate"]
        return merged

    @property
    def qos(self):
        """The TenantRegistry view the HTTP front-end resolves API
        keys against (replica 0's — every replica parses the same
        config, so the key map agrees fleet-wide)."""
        return getattr(self.replicas[0], "qos", None)

    def tenant_stats(self) -> dict:
        """FLEET-wide per-tenant stats: every replica's
        TenantRegistry.stats() merged — counters sum, weight/priority
        come from the shared config, and fair_share is recomputed from
        the merged generated totals (a per-replica ratio would not
        average meaningfully)."""
        merged: dict[str, dict] = {}
        for r in self.replicas:
            reg = getattr(r, "qos", None)
            if reg is None:
                continue
            for name, s in reg.stats().items():
                cur = merged.setdefault(name, {
                    "weight": s["weight"], "priority": s["priority"],
                    "pending": 0, "submitted": 0, "rejected": 0,
                    "generated": 0, "preempt_requeues": 0,
                    "prefill_tokens": 0, "spec_drafted": 0,
                    "spec_accepted": 0, "spec_wasted": 0})
                for k in ("pending", "submitted", "rejected",
                          "generated", "preempt_requeues",
                          "prefill_tokens", "spec_drafted",
                          "spec_accepted", "spec_wasted"):
                    cur[k] += s[k]
        from cloud_server_tpu.inference.qos import compute_fair_shares
        shares = compute_fair_shares(
            {name: (s["weight"], float(s["generated"]))
             for name, s in merged.items()})
        for name, s in merged.items():
            s["fair_share"] = shares[name]
        return merged

    def speculation_stats(self) -> dict:
        """FLEET-wide speculation summary (the /stats `speculation`
        source behind the router): drafted/accepted counts sum across
        replicas and `accept_rate` recomputes from the merged totals
        (a per-replica ratio would not average meaningfully —
        exactly the `tenant_fair_share` rule). Per-replica live
        `draft_lens` views are dropped (slot ids are replica-local)."""
        merged: dict = {}
        for r in self.replicas:
            fn = getattr(r, "speculation_stats", None)
            if fn is None:
                continue
            s = fn()
            if not merged:
                merged = {
                    "enabled": s["enabled"], "source": s["source"],
                    "max_drafts": s["max_drafts"],
                    "adaptive": s["adaptive"],
                    "tokens_drafted": 0, "tokens_accepted": 0}
            elif s["enabled"] and not merged["enabled"]:
                # heterogeneous fleet: config metadata must come from a
                # replica that actually speculates, not whichever
                # answered first — otherwise /stats could report
                # source "off" alongside nonzero drafted counts
                merged.update(source=s["source"],
                              max_drafts=s["max_drafts"],
                              adaptive=s["adaptive"])
            merged["enabled"] = merged["enabled"] or s["enabled"]
            merged["tokens_drafted"] += s["tokens_drafted"]
            merged["tokens_accepted"] += s["tokens_accepted"]
        if merged:
            merged["accept_rate"] = (merged["tokens_accepted"]
                                     / max(merged["tokens_drafted"], 1))
        return merged

    def cache_stats(self) -> dict:
        """FLEET-wide KV-cache/memory view (the /debug/cache and
        /stats `cache` source behind the router): pool, prefix, and
        per-tenant COUNTS sum across replicas; `hit_rate` and
        `evictable_frac` recompute from the merged totals (never
        added — the `tenant_fair_share` ratio rule); the hot-prefix
        sketches merge per chain digest (hits sum, so the same system
        prompt hot on two replicas ranks twice as hot fleet-wide —
        the artifact ROADMAP item 3(a)'s prefix-aware `_pick` scores
        against); forensics rings concatenate tagged by replica.
        Returns {} when no replica exposes cache stats."""
        from cloud_server_tpu.inference.cache_telemetry import (
            merge_cache_stats)
        stats = []
        for r in self.replicas:
            fn = getattr(r, "cache_stats", None)
            if fn is not None:
                stats.append(fn())
        return merge_cache_stats(stats)

    def lookup_trace(self, request_id: str) -> dict | None:
        """Span tree for one sampled request, wherever it ran: the
        first replica that knows the id answers, tagged with its
        replica index (router-submitted requests already carry it from
        the router_pick span).  In a role-specialized fleet a
        handed-off request's prefill and decode halves merge into the
        ONE spanning tree; looking up either the original or the
        continuation id returns that merged tree."""
        tree = None
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "lookup_trace", None)
            tree = fn(request_id) if fn is not None else None
            if tree is not None:
                tree["root"]["tags"].setdefault("replica", i)
                break
        # analysis: allow[lock-discipline] racy-by-design monitoring
        # read of a GIL-atomic bool (flips under _lock)
        if tree is None or not self._disagg:
            return tree
        for t in self.trace_trees():
            tags = t["root"]["tags"]
            if (t["request_id"] == request_id
                    or request_id in tags.get("handoff_segments", ())):
                return t
        return tree

    def trace_trees(self, n: int | None = None) -> list[dict]:
        """FLEET-wide sampled span trees (the /traces source), each
        tagged with its replica index and ordered by root start
        (n <= 0 means "no trees", the recorder's own rule).  Handoff
        continuations merge into their original's tree
        (request_trace.merge_handoff_trees) so a disaggregated request
        reads as one gap-free tree spanning both replicas."""
        if n is not None and n <= 0:
            return []
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "trace_trees", None)
            if fn is None:
                continue
            for tree in fn(n):
                tree["root"]["tags"].setdefault("replica", i)
                out.append(tree)
        # analysis: allow[lock-discipline] racy-by-design monitoring
        # read of a GIL-atomic bool (flips under _lock)
        if self._disagg:
            from cloud_server_tpu.inference.request_trace import (
                merge_handoff_trees)
            out = merge_handoff_trees(out)
        out.sort(key=lambda t: t["root"]["start"])
        return out if n is None else out[-n:]

    def slo_report(self) -> dict | None:
        """FLEET-wide SLO attainment + burn rates: every replica's
        report merged by summing good/total counts per (class, metric,
        window) and recomputing the ratios — the control signal the
        future autoscaler consumes. None when no replica tracks
        SLOs."""
        from cloud_server_tpu.inference.slo import merge_reports
        return merge_reports(
            r.slo_report() for r in self.replicas
            if hasattr(r, "slo_report"))

    def flight_window(self, n: int | None = None) -> list[dict]:
        """Recent flight-recorder records across the fleet, each tagged
        with its replica index, ordered by wall-clock timestamp."""
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "flight_window", None)
            if fn is not None:
                out += [{"replica": i, "role": self.roles[i], **rec}
                        for rec in fn(n)]
        out.sort(key=lambda rec: rec.get("ts", 0.0))
        return out

    def anomaly_stats(self) -> dict | None:
        """FLEET-wide watchdog view (anomaly.merge_anomaly_stats):
        per-rule fire counts sum, active windows union, event rings
        interleaved by start time with each event tagged by its TRUE
        replica index (pre-tagged here — the merge helper's own
        enumeration only covers replicas that HAVE a watchdog). None
        when no replica has one."""
        from cloud_server_tpu.inference.anomaly import (
            merge_anomaly_stats)
        stats = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "anomaly_stats", None)
            s = fn() if fn is not None else None
            if s is not None:
                s = dict(s)
                s["events"] = [dict(ev, replica=ev.get("replica", i))
                               for ev in s.get("events", ())]
                stats.append(s)
        return merge_anomaly_stats(stats)

    def anomaly_events(self, n: int | None = None) -> list[dict]:
        """Fleet anomaly events for the /traces marker track, each
        tagged with its replica, ordered by window start."""
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "anomaly_events", None)
            if fn is not None:
                out += [dict(ev, replica=ev.get("replica", i))
                        for ev in fn(n)]
        out.sort(key=lambda e: e["start"])
        return out if n is None or n <= 0 else out[-n:]

    def tail_trace_trees(self, n: int | None = None) -> list[dict]:
        """FLEET-wide tail-retained span trees, replica-tagged and
        handoff-merged exactly like trace_trees — the retention
        predicate is replica-deterministic (both halves of a handoff
        always retain), so a disaggregated anomalous request reads as
        ONE gap-free tree here."""
        if n is not None and n <= 0:
            return []
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "tail_trace_trees", None)
            if fn is None:
                continue
            for tree in fn(n):
                tree["root"]["tags"].setdefault("replica", i)
                out.append(tree)
        # analysis: allow[lock-discipline] racy-by-design monitoring
        # read of a GIL-atomic bool (flips under _lock)
        if self._disagg:
            from cloud_server_tpu.inference.request_trace import (
                merge_handoff_trees)
            out = merge_handoff_trees(out)
        out.sort(key=lambda t: t["root"]["start"])
        return out if n is None else out[-n:]

    def tail_trace_stats(self) -> dict | None:
        """Fleet tail-retention accounting: capacities and counts sum
        across replicas (per-reason retained_total merges per key).
        None when no replica retains tail traces."""
        merged: dict | None = None
        for r in self.replicas:
            fn = getattr(r, "tail_trace_stats", None)
            s = fn() if fn is not None else None
            if s is None:
                continue
            if merged is None:
                merged = {"capacity": 0, "retained": 0,
                          "retained_total": {}, "evicted_total": 0}
            merged["capacity"] += s["capacity"]
            merged["retained"] += s["retained"]
            merged["evicted_total"] += s["evicted_total"]
            for k, v in s["retained_total"].items():
                merged["retained_total"][k] = (
                    merged["retained_total"].get(k, 0) + v)
        return merged

    def debug_bundle(self, n: int = 64, *,
                     trigger: str = "manual") -> dict:
        """FLEET-wide forensic bundle (the GET /debug/bundle payload
        behind the router): the same schema as a single replica's,
        assembled from the router's own merged views — counts summed,
        trees replica-tagged and handoff-merged, plus the
        router-only breaker/role blocks."""
        return {
            "schema": "cloud_server.debug_bundle/v1",
            "trigger": trigger,
            "ts": time.time(),
            "anomaly": self.anomaly_stats(),
            "metrics": self.metrics_snapshot(),
            "flight": self.flight_window(n),
            "traces": self.trace_trees(n),
            "tail_traces": self.tail_trace_trees(n),
            "tail_retention": self.tail_trace_stats(),
            "slo": self.slo_report(),
            "cache": self.cache_stats(),
            "migration": self.migration_stats(),
            "breakers": self.breaker_states(),
            "roles": self.replica_roles(),
        }

    def debug_bundles(self, n: int | None = None) -> list[dict]:
        """Auto-captured bundles across the fleet, each tagged with
        the replica whose watchdog snapshotted it, oldest first."""
        out = []
        for i, r in enumerate(self.replicas):
            fn = getattr(r, "debug_bundles", None)
            if fn is not None:
                out += [dict(b, replica=i) for b in fn(n)]
        out.sort(key=lambda b: b.get("ts", 0.0))
        return out if n is None or n <= 0 else out[-n:]

    def step(self) -> int:
        busy = 0
        for i, r in enumerate(self.replicas):
            try:
                busy += r.step()
            except Exception as exc:  # noqa: BLE001 — replica crash
                # a synchronously-driven replica whose scheduler throws
                # gets the same teardown serve_forever would give it:
                # stop accepting, fail its in-flight work (the failover
                # hooks retry zero-token requests on healthy replicas),
                # and trip its breaker — the other replicas keep
                # stepping instead of the whole fleet dying with it
                self._record_breaker_failure(i)
                stop_ev = getattr(r, "_stop", None)
                fail = getattr(r, "_fail_all", None)
                if stop_ev is None or fail is None:
                    raise
                stop_ev.set()
                fail(exc)
        return busy

    def run_until_idle(self) -> None:
        while any(r.num_pending or r.num_active
                  or getattr(r, "_jobs", ())
                  for r in self.replicas):
            self.step()

    def start(self) -> "ReplicatedRouter":
        for r in self.replicas:
            r.start()
        return self

    # -- runtime fleet mutation ---------------------------------------------

    def attached_indices(self) -> list[int]:
        """Indices currently backed by a live replica (detached
        tombstones excluded) — the autoscaler's fleet-size view."""
        with self._lock:
            return [i for i in range(len(self.replicas))
                    if i not in self._detached]

    def _set_role_gauge_locked(self, i: int, old_role: str | None,
                               new_role: str | None) -> None:
        """Move the constant role gauge to the slot's current role
        (labeled series persist once created, so the stale label must
        be zeroed, not abandoned at 1)."""
        help_text = ("Replica role assignment (constant 1; the role "
                     "rides the labels)")
        if old_role is not None and old_role != new_role:
            self._registry.gauge(
                "router_replica_role", help_text,
                labels={"replica": str(i), "role": old_role}).set(0)
        if new_role is not None:
            self._registry.gauge(
                "router_replica_role", help_text,
                labels={"replica": str(i), "role": new_role}).set(1)

    def _recompute_disagg_locked(self) -> None:
        attached_roles = {self.roles[i]
                          for i in range(len(self.replicas))
                          if i not in self._detached}
        was = self._disagg
        self._disagg = (ROLE_PREFILL in attached_roles
                        and ROLE_DECODE in attached_roles)
        if self._disagg and not was and self._handoff_thread is None:
            # the fleet just became disaggregated at runtime: start
            # the handoff worker the constructor would have started
            self._handoff_q = queue.SimpleQueue()
            self._handoff_thread = threading.Thread(
                target=self._handoff_worker, daemon=True,
                name="router-handoff")
            self._handoff_thread.start()
        # a fleet that DEGRADED out of disaggregation (one side
        # removed) keeps its worker parked on the queue — harmless,
        # and re-adding the role reuses it

    def add_replica(self, replica, *, role: str = ROLE_COLOCATED) -> int:
        """Attach a replica to the serving fleet AT RUNTIME (the
        autoscaler's scale-up actuator; equally an operator handing a
        warm standby to a live router). Returns the replica's index.

        Registration matches the constructor: fresh breaker, role +
        breaker-state gauges, failover/handoff capability probes.
        Detached indices (prior `remove_replica`) are reused before
        the arrays grow. A quiesced replica (a drained one coming
        back from a warm pool) is `resume()`d so it accepts work the
        moment placement can see it.

        Roles: unlike the constructor — which validates the INITIAL
        fleet shape — incremental adds accept any valid role;
        disaggregated routing switches on automatically once the
        attached fleet has both a 'prefill' and a 'decode' replica."""
        if role not in _VALID_ROLES:
            raise ValueError(f"unknown replica role {role!r}; valid: "
                             f"{sorted(_VALID_ROLES)}")
        # capability probes (inspect.signature) stay outside the lock
        takes_hook = self._submit_takes_hook(replica)
        takes_handoff = self._submit_takes_hook(replica, "handoff")
        if (not getattr(replica, "ready", True)
                and hasattr(replica, "resume")):
            replica.resume()
        with self._lock:
            if self._detached:
                i = min(self._detached)
                self._detached.discard(i)
                old_role = self.roles[i]
                self.replicas[i] = replica
                self.roles[i] = role
                self._inflight[i] = 0
                self._breakers[i] = _Breaker()
                self._accepts_hook[i] = takes_hook
                self._accepts_handoff[i] = takes_handoff
            else:
                i = len(self.replicas)
                old_role = None
                self.replicas.append(replica)
                self.roles.append(role)
                self._inflight.append(0)
                self._breakers.append(_Breaker())
                self._accepts_hook.append(takes_hook)
                self._accepts_handoff.append(takes_handoff)
                self._registry.gauge(
                    "router_breaker_state",
                    "Per-replica breaker state (0 closed, 1 "
                    "half_open, 2 open)",
                    labels={"replica": str(i)}).set(0)
            self._set_role_gauge_locked(i, old_role, role)
            self._recompute_disagg_locked()
        _log.info("replica %d attached (role=%s, fleet size %d)",
                  i, role, len(self.attached_indices()))
        return i

    def _quiesce_for_removal(self, replica_index: int, *,
                             timeout: float | None,
                             migrate: bool) -> bool:
        """remove_replica's drain step. Replicas with drain support
        get the full evacuating drain; a backend without drain() is
        removable only once idle (polled up to `timeout` — it cannot
        quiesce itself, so a busy one refuses removal instead of
        cutting off its in-flight work)."""
        src = self.replicas[replica_index]
        if callable(getattr(src, "drain", None)):
            return self.drain(replica_index, timeout=timeout,
                              migrate=migrate)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while src.num_active or src.num_pending:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def remove_replica(self, replica_index: int, *,
                       timeout: float | None = None,
                       migrate: bool = True):
        """Detach a replica AT RUNTIME (the autoscaler's scale-down
        actuator): drain it first — with `migrate=True` (default)
        every in-flight request is EVACUATED to a healthy replica at
        its exact next token, zero requests lost — then tombstone its
        index and hand the (quiesced, still-running) replica object
        back to the caller, who owns its lifecycle from here (stop it,
        or park it in a warm pool for a later `add_replica`).

        Returns None — with the replica still attached and serving —
        when the drain timed out; the caller retries or escalates.
        Concurrent `submit()`s are safe throughout: during the drain
        the replica is unready (placement skips it), and a submit that
        captured the index before detachment fails over on the
        tombstone's refusal."""
        with self._lock:
            n_attached = (len(self.replicas) - len(self._detached)
                          - len(self._removing))
            if (replica_index in self._detached
                    or replica_index in self._removing
                    or not 0 <= replica_index < len(self.replicas)):
                raise ValueError(
                    f"replica {replica_index} is not attached")
            if n_attached <= 1:
                raise ValueError(
                    "cannot remove the last attached replica; "
                    "stop() the router instead")
            # claim the index: a concurrent remove_replica of the same
            # slot (two autoscaler loops, an operator racing one) must
            # see "not attached", not drain a replica twice
            self._removing.add(replica_index)
        try:
            if not self._quiesce_for_removal(replica_index,
                                             timeout=timeout,
                                             migrate=migrate):
                # timed out: the replica resumed accepting (drain's
                # timeout contract) and STAYS attached
                _log.warning(
                    "remove_replica(%d): drain timed out; replica "
                    "stays attached", replica_index)
                return None
            with self._lock:
                replica = self.replicas[replica_index]
                old_role = self.roles[replica_index]
                self.replicas[replica_index] = _DetachedSlot()
                self.roles[replica_index] = ROLE_COLOCATED
                self._inflight[replica_index] = 0
                self._breakers[replica_index] = _Breaker()
                self._detached.add(replica_index)
                self._accepts_hook[replica_index] = False
                self._accepts_handoff[replica_index] = False
                self._set_role_gauge_locked(replica_index, old_role,
                                            None)
                self._recompute_disagg_locked()
        finally:
            with self._lock:
                self._removing.discard(replica_index)
        _log.info("replica %d detached (was role=%s, fleet size %d)",
                  replica_index, old_role,
                  len(self.attached_indices()))
        return replica

    def drain(self, replica_index: int, *,
              timeout: float | None = None,
              migrate: bool = True) -> bool:
        """Drain ONE replica for maintenance. With `migrate=True`
        (default) every active request is first EVACUATED: exported
        at the replica's commit point and resumed on a healthy
        replica at the exact next token, on the same stream — a
        zero-token-loss operation. Whatever cannot move (export
        fault, no healthy destination, non-migratable state) is
        waited out by the normal drain. Returns the replica drain's
        verdict (True = idle/quiesced; resume() it to serve again)."""
        src = self.replicas[replica_index]

        def _migrate_cb(snap, req) -> bool:
            t0 = time.perf_counter()
            excluded = {replica_index}
            kw = {"tenant": snap.tenant,
                  "stream": getattr(req, "stream", None)}
            while True:
                with self._lock:
                    i = self._pick(tenant=snap.tenant,
                                   count_inflight=True,
                                   exclude=excluded, strict=True)
                if i is None:
                    return False
                imp = getattr(self.replicas[i], "migrate_import", None)
                if imp is None:
                    with self._lock:
                        self._inflight[i] -= 1
                    self._release_probe(i)
                    excluded.add(i)
                    if len(excluded) >= len(self.replicas):
                        return False
                    continue
                self._m_migrations.inc()
                # analysis: allow[lock-discipline] GIL-atomic capability
                # slot (written once at attach under _lock), as in submit
                hook = (self._make_fail_hook(
                            i, list(snap.prompt), dict(kw),
                            frozenset(excluded), req)
                        if self._accepts_hook[i] else None)
                try:
                    new = imp(snap, stream=kw["stream"],
                              fail_handler=hook,
                              trace_ctx=continuation_ctx(req))
                except Exception as exc:  # noqa: BLE001 — next replica
                    with self._lock:
                        self._inflight[i] -= 1
                    if (isinstance(exc, RuntimeError)
                            and not isinstance(exc, QueueFullError)
                            and getattr(self.replicas[i], "ready",
                                        True)):
                        self._record_breaker_failure(i)
                    else:
                        self._release_probe(i)
                    excluded.add(i)
                    if len(excluded) >= len(self.replicas):
                        return False
                    continue
                with self._lock:
                    self._inflight[i] -= 1
                self._record_breaker_success(i)
                # same mirroring/cancel-chain contract as
                # _migrate_submit: the evacuated request handle stays
                # the client's, the destination's outcome mirrors back
                new._router_orig = req
                new._router_migrated = True
                new._on_done = self._mirror_retry
                with self._lock:
                    gen = len(excluded)
                    if gen >= getattr(req, "_router_cancel_gen", -1):
                        req._router_cancel_gen = gen
                        req._on_cancel = lambda _r, _n=new: _n.cancel()
                if req._cancel.is_set():
                    new.cancel()
                tr = any_trace(new)
                if tr is not None:
                    tr.annotate(replica=i, migrate_of=req.request_id)
                    tr.add_span("migrate", t0, time.perf_counter(),
                                from_replica=replica_index, replica=i,
                                reason="drain",
                                tokens_salvaged=len(snap.tokens),
                                kv_pages=snap.n_kv_pages())
                self._migration_ms.observe(
                    (time.perf_counter() - t0) * 1e3)
                if new.done:
                    self._mirror_retry(new)
                return True

        if migrate:
            try:
                return src.drain(timeout, migrate=_migrate_cb)
            except TypeError:
                # replica without migration support: fall through to
                # the plain wait-it-out drain below, VISIBLY
                _log.warning(
                    "replica %d drain() does not accept migrate=; "
                    "draining without evacuation", replica_index)
        return src.drain(timeout)

    def migration_stats(self) -> dict:
        """FLEET-wide live-migration counters (the /stats `migration`
        source behind the router): every replica's ledger sums
        (export + import halves), and `success_rate` — resumptions
        admitted per export attempted — recomputes from the merged
        totals (the `tenant_fair_share` ratio rule: ratios never
        add)."""
        keys = ("out_started", "out_completed", "out_failed",
                "in_started", "in_completed", "in_failed", "started",
                "completed", "failed", "tokens_salvaged",
                "pages_moved")
        merged = {k: 0 for k in keys}
        for r in self.replicas:
            fn = getattr(r, "migration_stats", None)
            if fn is None:
                continue
            s = fn()
            for k in keys:
                merged[k] += s.get(k, 0)
        merged["success_rate"] = (merged["in_completed"]
                                  / max(merged["out_started"], 1))
        return merged

    def stop(self, drain: bool = False,
             timeout: float | None = None) -> None:
        # analysis: allow[lock-discipline] teardown read of a
        # GIL-atomic write-once reference (never cleared)
        if self._handoff_q is not None:
            self._handoff_q.put(None)  # analysis: allow[lock-discipline] teardown, write-once reference; unblocks the handoff worker
        for i, r in enumerate(self.replicas):
            try:
                r.stop(drain=drain, timeout=timeout)
            except TypeError:
                # replica without drain support: retry drain-less —
                # but VISIBLY (counted + logged), because the drain
                # the caller asked for did not happen on this replica
                # and its in-flight work is about to be cut off
                self._m_drainless.inc()
                _log.warning(
                    "replica %d stop() does not accept drain/timeout; "
                    "stopping without drain (requested drain=%s "
                    "timeout=%s)", i, drain, timeout)
                r.stop()

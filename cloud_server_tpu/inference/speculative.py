"""Speculative decoding: propose G tokens per round, score the whole
window in one `verify_step` pass, commit an accepted prefix plus one
corrective token.

Two draft sources share one loop:
  * a small DRAFT MODEL (classic speculative decoding) — pass
    `draft_params`/`draft_cfg`;
  * PROMPT-LOOKUP (n-gram) drafting — pass `draft_params=None`: proposals
    are the tokens that followed the most recent earlier occurrence of
    the current bigram in the sequence so far. No second model, no extra
    memory; it wins on text with local repetition (code, structured
    data, retrieval-heavy prompts) and degrades to plain decoding
    (one committed token per round) when nothing matches.

Output-distribution exactness holds for BOTH sources: acceptance follows
the standard speculative-sampling rule — draft token d with draft
probability q(d) and target probability p(d) is accepted with prob
min(1, p(d)/q(d)); on first rejection the corrective token is drawn from
normalize(max(p - q, 0)); if all G drafts survive, a bonus token is drawn
from the target's distribution at the window's last position. For n-gram
drafting q is a point mass at the proposal, so the rule reduces to
"accept with prob p(d)" — still exact, whatever the proposals are. Both
p and q are the *post-filter* sampling distributions
(`sampling.sampling_probs`), so temperature/top-k/top-p semantics match
plain `generate`; at temperature 0 the rule reduces to exact-match greedy
and speculative output is identical to `generate`'s token-for-token.

Why this is the right shape for TPU decode: decode is HBM-bound (the full
weight set streams per token), so scoring G+1 positions in one pass costs
barely more than scoring one. Wall-clock per committed token drops by
roughly the mean accepted length; everything (draft, verify, accept,
commit, output scatter) runs inside ONE jitted `lax.while_loop` with
static shapes — no host round-trip per round.

Cache discipline — the target (and the draft model, when present) keeps
the invariant "at round start, every committed token EXCEPT the last has
been processed into the cache":
  * the draft model runs G+1 decode steps — the last one exists only to
    process its own G-th proposal so that when everything is accepted its
    cache is already caught up; its sample is discarded.
  * `verify_step` writes the window's kv entries but does not advance
    `length`; the commit just advances each sequence's length by the
    number of committed tokens. Stale entries past the commit point are
    masked by `kv_length` and overwritten by the next round's writes at
    the same positions — rollback is free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference.engine import (
    decode_step, init_cache, prefill, verify_step)
from cloud_server_tpu.inference.sampling import (
    sample_from_probs, sampling_probs)

# Per-request POSITION-KEYED speculative draws (the paged server's
# use_rows path): seeded requests promise "draws depend only on (seed,
# position), never on batch composition or schedule"
# (sampling._row_keys), and the speculative rule consumes three draw
# streams of its own — the draft model's proposal, the accept uniform,
# and the corrective/bonus sample, all at a definite absolute sequence
# position. Folding a stream tag on top of the same (seed, position)
# key keeps every stream independent of the others AND of the plain
# token-sampling draw (which uses the untagged key), so at a FIXED
# per-round draft length a seeded request's speculative stream is
# identical under any scheduler, and commit TRUNCATION (stop_len /
# draft_limit caps) replays transparently — the unconsumed positions
# re-draw the same values next round from the same prefix. Changing
# the draft length itself mid-stream (the adaptive controller) is NOT
# draw-invariant at temperature > 0: a position that falls on one
# schedule's all-accepted bonus draw is another schedule's draft +
# accept, so adaptive seeded runs stay exact in DISTRIBUTION but are
# reproducible only per length schedule (greedy is always exact).
_TAG_DRAFT, _TAG_ACCEPT, _TAG_RESIDUAL = 101, 102, 103


def _row_pos_keys(seeds, positions, tag: int):
    """(N,) uint32 seeds + (N,) int32 absolute positions -> (N,) keys
    on the `tag` stream (disjoint from token sampling's untagged
    fold_in(key(seed), position))."""
    def mk(seed, pos):
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), pos), tag)

    return jax.vmap(mk)(seeds, positions)


def sample_from_probs_keyed(probs, keys):
    """Per-row categorical draw: (B, V) probabilities with (B,) keys
    -> (B,) int32 (the keyed counterpart of sample_from_probs)."""
    return jax.vmap(
        lambda k, p: jax.random.categorical(
            k, jnp.log(jnp.maximum(p, 1e-30))))(keys, probs).astype(
                jnp.int32)


def _accept_uniforms(rng_u, b: int, g: int, seeds, pos0):
    """The (B, G) accept uniforms: dispatch-rng (seeds None) or
    position-keyed per row on the _TAG_ACCEPT stream (u for drafts[:,
    j] keyed at the draft's absolute position pos0 + j)."""
    if seeds is None:
        return jax.random.uniform(rng_u, (b, g))
    upos = (pos0[:, None] + jnp.arange(g)[None, :]).reshape(-1)
    keys = _row_pos_keys(jnp.repeat(seeds, g), upos, _TAG_ACCEPT)
    return jax.vmap(
        lambda k: jax.random.uniform(k, ()))(keys).reshape(b, g)


def _residual_draw(rng_x, residual, n_acc, seeds, pos0):
    """The corrective/bonus draw: dispatch-rng, or keyed at the
    corrective's absolute position (pos0 + n_acc) on _TAG_RESIDUAL."""
    if seeds is None:
        return sample_from_probs(residual, rng_x)
    keys = _row_pos_keys(seeds, pos0 + n_acc, _TAG_RESIDUAL)
    return sample_from_probs_keyed(residual, keys)


def _accept_drafts(drafts, q_probs, p_probs, rng, *, seeds=None,
                   pos0=None):
    """Vectorised accept/residual rule.

    drafts: (B, G) proposed tokens; q_probs: (B, G, V) draft sampling
    distributions; p_probs: (B, G+1, V) target sampling distributions
    (position j scores drafts[:, j]; position G is the bonus position).
    `seeds`/`pos0` ((B,) uint32 / (B,) int32 absolute position of
    drafts[:, 0]) switch the u and corrective draws to the per-request
    position-keyed streams (see _TAG_* above); None keeps the
    dispatch-rng draws.

    Returns (n_accepted (B,) int32 in [0, G], corrective token x (B,)).
    """
    b, g = drafts.shape
    rng_u, rng_x = jax.random.split(rng)
    batch_idx = jnp.arange(b)

    q_d = jnp.take_along_axis(q_probs, drafts[..., None], axis=-1)[..., 0]
    p_d = jnp.take_along_axis(p_probs[:, :g], drafts[..., None],
                              axis=-1)[..., 0]
    u = _accept_uniforms(rng_u, b, g, seeds, pos0)
    accept = u * jnp.maximum(q_d, 1e-30) < p_d  # u < min(1, p/q)
    prefix = jnp.cumprod(accept.astype(jnp.int32), axis=-1)
    n_acc = prefix.sum(axis=-1)  # (B,) in [0, G]

    # Residual at the first rejected position; when n_acc == G there is no
    # rejection and the "residual" is the bonus position's target
    # distribution unmodified (q contribution zeroed).
    p_r = p_probs[batch_idx, n_acc]  # (B, V)
    q_pad = jnp.concatenate([q_probs, jnp.zeros_like(q_probs[:, :1])],
                            axis=1)
    q_r = jnp.where((n_acc < g)[:, None], q_pad[batch_idx, n_acc], 0.0)
    residual = jnp.maximum(p_r - q_r, 0.0)
    # If float round-off leaves residual empty, fall back to p itself.
    bad = residual.sum(-1, keepdims=True) <= 0.0
    residual = jnp.where(bad, p_r, residual)
    x = _residual_draw(rng_x, residual, n_acc, seeds, pos0)
    return n_acc, x


def _accept_point_mass(drafts, p_probs, rng, *, seeds=None, pos0=None):
    """`_accept_drafts` specialised to point-mass q (n-gram drafting):
    q(d) = 1, so acceptance is `u < p(d)` and the residual is p with the
    rejected proposal's index zeroed — computed directly, without
    materialising the (B, G, V) one-hot q tensor in the hot decode loop.
    `seeds`/`pos0` select the position-keyed draw streams as in
    `_accept_drafts`.
    """
    b, g = drafts.shape
    rng_u, rng_x = jax.random.split(rng)
    batch_idx = jnp.arange(b)

    p_d = jnp.take_along_axis(p_probs[:, :g], drafts[..., None],
                              axis=-1)[..., 0]
    u = _accept_uniforms(rng_u, b, g, seeds, pos0)
    prefix = jnp.cumprod((u < p_d).astype(jnp.int32), axis=-1)
    n_acc = prefix.sum(axis=-1)

    p_r = p_probs[batch_idx, n_acc]  # (B, V)
    rejected = jnp.where(n_acc < g, drafts[batch_idx,
                                           jnp.minimum(n_acc, g - 1)], -1)
    residual = jnp.where(
        (jnp.arange(p_r.shape[-1])[None, :] == rejected[:, None])
        & (n_acc < g)[:, None], 0.0, p_r)
    bad = residual.sum(-1, keepdims=True) <= 0.0
    residual = jnp.where(bad, p_r, residual)
    x = _residual_draw(rng_x, residual, n_acc, seeds, pos0)
    return n_acc, x


def _ngram_drafts(hist, valid, t_prev2, t_prev, g, pad):
    """Prompt-lookup proposals: find the latest earlier occurrence of the
    bigram (t_prev2, t_prev) in `hist[:, :valid]` and propose the G tokens
    that followed it.

    hist: (B, H) committed tokens (prompt + generated), pad beyond
    `valid`; t_prev2/t_prev: the last two committed tokens. Positions
    with no match (or running off the committed region) propose `pad` —
    an ordinary (usually wrong) proposal the accept rule scores like any
    other, so exactness is unaffected.
    """
    bsz, hl = hist.shape
    i = jnp.arange(hl - 1)
    match = ((hist[:, :-1] == t_prev2[:, None])
             & (hist[:, 1:] == t_prev[:, None])
             # strictly BEFORE the current occurrence at (valid-2, valid-1)
             & (i[None, :] + 1 < (valid - 1)[:, None]))
    last = jnp.max(jnp.where(match, i, -1), axis=1)  # (B,)
    found = last >= 0
    pos = (last + 2)[:, None] + jnp.arange(g)[None, :]  # (B, G)
    ok = found[:, None] & (pos < valid[:, None])
    gathered = jnp.take_along_axis(hist, jnp.clip(pos, 0, hl - 1), axis=1)
    return jnp.where(ok, gathered, pad)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "infer_cfg", "num_draft",
                     "max_len"))
def speculative_generate(params, draft_params, prompt: jnp.ndarray,
                         rng: jax.Array, *, cfg: ModelConfig,
                         draft_cfg: ModelConfig | None = None,
                         infer_cfg: InferConfig,
                         num_draft: int = 4, max_len: int | None = None,
                         prompt_lengths: jnp.ndarray | None = None
                         ) -> jnp.ndarray:
    """Speculative counterpart of `engine.generate` — same contract:
    prompt (B, P) int32 right-padded (pass prompt_lengths when ragged),
    returns (B, max_decode_len) int32 with pad after eos.

    `draft_params`/`draft_cfg` select the draft source: a small model
    sharing the target's tokenizer/vocab, or None/None for prompt-lookup
    (n-gram) drafting. `num_draft` (G) proposals are scored per round.
    """
    use_ngram = draft_params is None
    if use_ngram != (draft_cfg is None):
        raise ValueError("pass draft_params and draft_cfg together "
                         "(both None selects n-gram drafting)")
    b, p = prompt.shape
    g = num_draft
    n_new = infer_cfg.max_decode_len
    pad = infer_cfg.pad_token_id
    # + g + 1 slack: the final round's window may overhang the output.
    max_len = max_len or (p + n_new + g + 1)
    if max_len < p + n_new + g + 1:
        raise ValueError(
            f"max_len={max_len} < prompt ({p}) + max_decode_len ({n_new}) "
            f"+ window slack ({g + 1}); the cache would silently wrap")

    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt, cfg, cache, prompt_lengths)
    if use_ngram:
        d_cache = None
    else:
        d_cache = init_cache(draft_cfg, b, max_len)
        _, d_cache = prefill(draft_params, prompt, draft_cfg, d_cache,
                             prompt_lengths)

    plen = (jnp.full((b,), p, jnp.int32) if prompt_lengths is None
            else prompt_lengths.astype(jnp.int32))
    rng, rng0 = jax.random.split(rng)
    t_prev = sample_from_probs(sampling_probs(logits, infer_cfg), rng0)
    done0 = t_prev == infer_cfg.eos_token_id
    out = jnp.full((b, n_new + g + 1), pad, jnp.int32)
    # the eos itself is emitted (matching generate); only LATER tokens pad
    out = out.at[:, 0].set(t_prev)
    # token 0 comes from prefill logits, mirroring `generate`
    n_emit0 = jnp.ones((b,), jnp.int32)
    batch_idx = jnp.arange(b)
    j = jnp.arange(g + 1)[None, :]  # (1, G+1)

    # committed-token history (prompt + generated) for n-gram lookup
    hist0 = jnp.full((b, p + n_new + g + 1), pad, jnp.int32)
    hist0 = lax.dynamic_update_slice(hist0, prompt, (0, 0))
    hist0 = hist0.at[batch_idx, plen].set(t_prev)
    t_prev2_0 = hist0[batch_idx, jnp.maximum(plen - 1, 0)]

    def round_body(state):
        (rnd, rng, t_prev, t_prev2, done, n_emit, out, hist, cache,
         d_cache) = state
        rng, r_draft, r_acc = jax.random.split(
            jax.random.fold_in(rng, rnd), 3)

        if use_ngram:
            valid = plen + n_emit
            drafts = _ngram_drafts(hist, valid, t_prev2, t_prev, g, pad)
            q_probs = None  # point mass; _accept_point_mass handles it
            d_cache2 = d_cache
        else:
            # --- draft model: G+1 decode steps (see module docstring) ---
            def d_step(carry, rng_t):
                tok, dc = carry
                dlogits, dc = decode_step(draft_params, tok, draft_cfg, dc)
                qp = sampling_probs(dlogits, infer_cfg)
                nxt = sample_from_probs(qp, rng_t)
                return (nxt, dc), (nxt, qp)

            (_, d_cache2), (draft_toks, q_probs) = lax.scan(
                d_step, (t_prev, d_cache), jax.random.split(r_draft, g + 1))
            drafts = draft_toks[:g].T  # (B, G)
            q_probs = q_probs[:g].transpose(1, 0, 2)  # (B, G, V)

        # --- verify the whole window in one target pass ---
        window = jnp.concatenate([t_prev[:, None], drafts], axis=1)
        vlogits, cache2 = verify_step(params, window, cfg, cache)
        p_probs = sampling_probs(vlogits, infer_cfg)  # (B, G+1, V)

        if use_ngram:
            n_acc, x = _accept_point_mass(drafts, p_probs, r_acc)
        else:
            n_acc, x = _accept_drafts(drafts, q_probs, p_probs, r_acc)

        # --- commit d_1..d_{n_acc} then x, truncated at the first eos ---
        drafts_x = jnp.concatenate([drafts, x[:, None]], axis=1)  # (B,G+1)
        committed = jnp.where(
            j < n_acc[:, None], drafts_x,
            jnp.where(j == n_acc[:, None], x[:, None], pad))
        is_eos = committed == infer_cfg.eos_token_id
        first_eos = jnp.argmax(is_eos, axis=1)
        has_eos = is_eos.any(axis=1)
        count = jnp.where(has_eos, jnp.minimum(n_acc + 1, first_eos + 1),
                          n_acc + 1)
        count = jnp.where(done, 0, count)
        emit = jnp.where(j < count[:, None], committed, pad)

        # scatter into each sequence's next output slots; writes past
        # `count` land on not-yet-filled pad slots (harmless), writes past
        # the buffer drop.
        cols = n_emit[:, None] + j  # (B, G+1)
        out2 = out.at[batch_idx[:, None], cols].set(emit, mode="drop")
        hist2 = hist.at[batch_idx[:, None],
                        plen[:, None] + cols].set(emit, mode="drop")

        new_len = cache.length + count
        cache3 = cache2._replace(length=new_len)
        d_cache3 = (None if use_ngram
                    else d_cache2._replace(length=new_len))
        done2 = done | (has_eos & (first_eos < count))
        n_emit2 = n_emit + count
        last_idx = jnp.maximum(count - 1, 0)
        t_next = jnp.where(count > 0, committed[batch_idx, last_idx],
                           t_prev)
        valid2 = plen + n_emit2
        t_prev2_next = hist2[batch_idx, jnp.maximum(valid2 - 2, 0)]
        return (rnd + 1, rng, t_next, t_prev2_next, done2, n_emit2, out2,
                hist2, cache3, d_cache3)

    def cond(state):
        rnd, _, _, _, done, n_emit, *_ = state
        # every active round commits >= 1 token, so n_new rounds suffice
        return (rnd < n_new) & jnp.any(~done & (n_emit < n_new))

    state = (jnp.int32(0), rng, t_prev, t_prev2_0, done0, n_emit0, out,
             hist0, cache, d_cache)
    state = lax.while_loop(cond, round_body, state)
    return state[6][:, :n_new]

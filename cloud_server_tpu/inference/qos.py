"""Multi-tenant QoS: fair-share admission, priority preemption, and
per-tenant isolation for the serving stack.

Production serving is multi-tenant: many products/users share one
engine, and without a policy layer a single flooding tenant owns the
FIFO queue, the page pool, and the 429 budget of everyone else. This
module is that policy layer — pure host-side state the schedulers
consult at points they already own, so it adds ZERO device dispatches
or syncs (the `analysis/` hot-path lint and the dispatch-count
regression tests enforce this):

  * `TenantConfig` / `TenantRegistry` — per-tenant weight, priority
    class (interactive > batch > best_effort), token-bucket rate
    limits (prompt and generated tokens/s), per-tenant pending bounds,
    and API-key -> tenant mapping. Configured from a JSON object, a
    JSON string, or a file path (`InferConfig.qos_config`, server
    `qos=`, CLI `--qos-config`).
  * Weighted fair-share admission — DEFICIT ROUND-ROBIN over tenants
    when the scheduler picks which pending request gets the next free
    slot (`next_admission_index`), and weighted-fair ordering of the
    in-flight admission jobs that fund each mixed iteration's prefill
    chunks (`order_jobs` / `charge_prefill`). FIFO order is preserved
    WITHIN a tenant; with a single (default) tenant the selection
    degenerates to exactly the old FIFO.
  * Priority-aware preemption — on page-pool exhaustion the victim is
    chosen by (lowest priority class, most over fair share, youngest)
    instead of youngest-only (`priority_rank` + the server's weighted
    usage scan).
  * Differentiated backpressure — a tenant at its own pending bound or
    out of prompt-bucket budget gets `TenantQueueFullError` (HTTP 429
    with a `Retry-After` derived from its token-bucket refill) while
    every other tenant keeps admitting.

With no QoS config (`registry is None`) every server path is the
pre-QoS code byte-for-byte: the schedulers guard every call site with
`if self.qos is not None`, and the mixed-vs-alternating exact-output
tests pin the default behavior.

Work-conservation note: a tenant in generated-token debt is SKIPPED by
admission only while some other tenant is eligible; when every
backlogged tenant is over budget the pick falls back to plain DRR —
rate limits shape contended capacity, they never idle the chip.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time

from cloud_server_tpu.inference.server import QueueFullError

DEFAULT_TENANT = "default"

# Priority classes, best first. Preemption victimizes the HIGHEST rank
# (lowest class) first; admission share is set by weight, not class, so
# best-effort tenants still make progress under interactive floods.
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")

# Half-life of the DECAYED generated-token rate used for preemption's
# "most over fair share" key. Lifetime totals would let days of stale
# history pick victims (an established tenant's millions of old tokens
# outweighing a fresh flood); a ~30 s horizon ranks by what tenants are
# consuming NOW.
RECENT_USAGE_HALFLIFE_S = 30.0


def compute_fair_shares(
        entries: dict[str, tuple[float, float]]) -> dict[str, float]:
    """{name: (weight, generated)} -> {name: share / entitlement}.
    1.0 = the tenant holds exactly its weighted share of all generated
    tokens. THE fair-share definition — the registry's stats/gauges and
    ReplicatedRouter's fleet merge both call this, so the single-server
    and fleet views can never diverge."""
    total_gen = sum(g for _, g in entries.values())
    total_w = sum(w for w, _ in entries.values())
    out = {}
    for name, (w, g) in entries.items():
        share = (g / total_gen) if total_gen else 0.0
        entitlement = w / total_w if total_w else 1.0
        out[name] = share / entitlement if entitlement else 0.0
    return out


class TenantQueueFullError(QueueFullError):
    """Per-tenant backpressure: THIS tenant is over its pending bound
    or out of token-bucket budget; other tenants keep admitting. The
    HTTP front-end maps it to a 429 whose `Retry-After` header and
    structured body carry `retry_after_s` and `tenant`."""

    def __init__(self, message: str, *, tenant: str,
                 retry_after_s: float):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """Static per-tenant policy (see docs/serving.md for the JSON
    schema). `weight` sets the fair share; `priority` only orders
    preemption victims; rate/burst pairs of None disable that bucket;
    `max_pending` of None falls back to the server-wide bound."""

    name: str
    weight: float = 1.0
    priority: str = "interactive"
    max_pending: int | None = None
    prompt_tokens_per_s: float | None = None
    prompt_burst: float | None = None
    generated_tokens_per_s: float | None = None
    generated_burst: float | None = None
    api_keys: tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0 (a zero "
                "weight would starve the tenant forever; use "
                "priority='best_effort' for a preemption-first class)")
        if self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: unknown priority "
                f"{self.priority!r}; one of {PRIORITY_CLASSES}")
        if self.max_pending is not None and self.max_pending < 0:
            raise ValueError(
                f"tenant {self.name!r}: max_pending must be >= 0")
        for rate, burst, what in (
                (self.prompt_tokens_per_s, self.prompt_burst, "prompt"),
                (self.generated_tokens_per_s, self.generated_burst,
                 "generated")):
            if rate is not None and rate <= 0:
                raise ValueError(
                    f"tenant {self.name!r}: {what}_tokens_per_s must "
                    "be > 0 (omit it to disable the limit)")
            if burst is not None and rate is None:
                raise ValueError(
                    f"tenant {self.name!r}: {what}_burst without "
                    f"{what}_tokens_per_s")
            if burst is not None and burst <= 0:
                raise ValueError(
                    f"tenant {self.name!r}: {what}_burst must be > 0 "
                    "(a zero burst would reject every request forever)")


class TokenBucket:
    """Classic token bucket with debt. `try_consume` gates work before
    it happens (prompt tokens at submit); `charge` records work after
    the fact and may drive the level negative (generated tokens are
    only known post-emit) — a tenant in debt is deprioritized, never
    retroactively blocked. `retry_after` is the refill time until `n`
    tokens are available: the number the 429 path surfaces."""

    def __init__(self, rate: float, burst: float | None = None, *,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError("token bucket rate must be > 0")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._level = self.burst  # start full: bursts up to burst size
        self._clock = clock
        self._stamp = clock()

    def _refill(self, now: float) -> None:
        dt = now - self._stamp
        if dt > 0:
            self._level = min(self.burst, self._level + dt * self.rate)
            self._stamp = now

    def level(self, now: float | None = None) -> float:
        self._refill(self._clock() if now is None else now)
        return self._level

    def try_consume(self, n: float, now: float | None = None) -> bool:
        self._refill(self._clock() if now is None else now)
        if self._level >= n:
            self._level -= n
            return True
        return False

    def charge(self, n: float, now: float | None = None) -> None:
        self._refill(self._clock() if now is None else now)
        self._level -= n  # may go negative (debt)

    def retry_after(self, n: float = 0.0,
                    now: float | None = None) -> float:
        """Seconds until `n` tokens are available (0.0 when they
        already are). n=0 reports the time to climb out of debt."""
        self._refill(self._clock() if now is None else now)
        need = n - self._level
        return max(0.0, need / self.rate)


class _TenantState:
    """Runtime per-tenant bookkeeping (registry-private)."""

    def __init__(self, cfg: TenantConfig, clock):
        self.cfg = cfg
        self.prompt_bucket = (
            None if cfg.prompt_tokens_per_s is None else
            TokenBucket(cfg.prompt_tokens_per_s, cfg.prompt_burst,
                        clock=clock))
        self.generated_bucket = (
            None if cfg.generated_tokens_per_s is None else
            TokenBucket(cfg.generated_tokens_per_s, cfg.generated_burst,
                        clock=clock))
        # DRR state for slot admission + WFQ virtual time for mixed
        # prefill funding
        self.deficit = 0.0
        self.prefill_vt = 0.0
        # exponentially-decayed generated-token usage (see
        # RECENT_USAGE_HALFLIFE_S) — the preemption victim signal
        self.recent = 0.0
        self.recent_stamp = clock()
        # counters (host-side; mirrored into labeled metrics on the
        # scrape path, never the serving path)
        self.pending = 0
        self.submitted = 0
        self.rejected = 0
        self.generated = 0
        self.preempt_requeues = 0
        self.prefill_tokens = 0
        # speculative decoding: draft tokens proposed on the tenant's
        # rows vs accepted-and-committed. Only COMMITTED tokens are
        # billed to the generated bucket (charge_generated); the
        # difference is the tenant's wasted-speculation ledger
        self.spec_drafted = 0
        self.spec_accepted = 0


class TenantRegistry:
    """All QoS policy state, shared by a server's scheduler, its HTTP
    front-end, and the metrics scrape path. Methods that run inside
    the scheduler iteration are sync- and device-free (enforced by the
    `analysis/` hot-path lint); the internal lock only guards plain
    counter arithmetic, so contention is negligible.

    Config JSON shape::

        {"quantum": 256,
         "default": {"weight": 1.0},
         "tenants": {
           "team-a": {"weight": 3.0, "priority": "interactive",
                      "max_pending": 64,
                      "prompt_tokens_per_s": 2000, "prompt_burst": 8000,
                      "generated_tokens_per_s": 500,
                      "api_keys": ["key-a-1"]},
           "scraper": {"weight": 1.0, "priority": "best_effort"}}}

    Unknown tenants (and requests with no tenant at all) resolve to
    "default", whose policy is the optional "default" entry.
    """

    def __init__(self, config: dict | None = None, *,
                 clock=time.monotonic):
        config = dict(config or {})
        self._clock = clock
        self._lock = threading.Lock()
        self.quantum = float(config.get("quantum", 256))
        if self.quantum <= 0:
            raise ValueError("qos quantum must be > 0")
        # per-priority-class default request deadlines (seconds): a
        # number (every class) or {"interactive": 5, "batch": 60, ...}.
        # Applied at submit when the caller passes no deadline_s; the
        # scheduler sweep cancels expired requests (finish_reason
        # "deadline") and the router stops failover retries past them.
        dl = config.get("deadline_s")
        if dl is None:
            self._class_deadlines: dict[str, float] = {}
        elif isinstance(dl, (int, float)):
            self._class_deadlines = {c: float(dl)
                                     for c in PRIORITY_CLASSES}
        elif isinstance(dl, dict):
            unknown_cls = set(dl) - set(PRIORITY_CLASSES)
            if unknown_cls:
                raise ValueError(
                    f"deadline_s names unknown priority classes: "
                    f"{sorted(unknown_cls)}")
            self._class_deadlines = {c: float(v) for c, v in dl.items()}
        else:
            raise ValueError(
                "deadline_s must be a number or a class->seconds map")
        for c, v in self._class_deadlines.items():
            if v <= 0:
                raise ValueError(
                    f"deadline_s for {c!r} must be > 0 (omit the class "
                    "to leave it unbounded)")
        default = dict(config.get("default", {}))
        default.pop("api_keys", None)  # the fallback tenant has no keys
        self._states: dict[str, _TenantState] = {}
        self._order: list[str] = []  # config order; DRR iterates this
        self._api_keys: dict[str, str] = {}
        self._global_vt = 0.0
        # the tenant set is FROZEN here: configured tenants plus the
        # always-present default. resolve() collapses every other name
        # onto the default, so an untrusted X-Tenant header can neither
        # grow host state / metric cardinality without bound nor
        # multiply a flooder's fair share across spoofed names — and
        # the state dict stays safely iterable from the scrape thread
        # while the scheduler reads it.
        self._register(DEFAULT_TENANT,
                       TenantConfig(name=DEFAULT_TENANT, **default))
        for name, spec in dict(config.get("tenants", {})).items():
            spec = dict(spec)
            keys = tuple(spec.pop("api_keys", ()))
            cfg = TenantConfig(name=name, api_keys=keys, **spec)
            self._register(name, cfg)
            for k in keys:
                if k in self._api_keys:
                    raise ValueError(
                        f"api key registered for both "
                        f"{self._api_keys[k]!r} and {name!r}")
                self._api_keys[k] = name
        unknown = set(config) - {"quantum", "default", "tenants",
                                 "deadline_s"}
        if unknown:
            raise ValueError(f"unknown qos config keys: {sorted(unknown)}")

    def _register(self, name: str, cfg: TenantConfig) -> _TenantState:
        if name in self._states:
            raise ValueError(f"tenant {name!r} declared twice")
        st = _TenantState(cfg, self._clock)
        self._states[name] = st
        self._order.append(name)
        return st

    def _state(self, name: str) -> _TenantState:
        """State for a RESOLVED name — a plain dict read (the tenant
        set never changes after construction)."""
        return self._states[name]

    # -- identity -----------------------------------------------------------

    def resolve(self, tenant: str | None) -> str:
        """Canonical tenant name: configured names pass through;
        anything else — anonymous AND unknown names alike — collapses
        to "default", whose policy is the config's optional "default"
        entry (shared bucket, shared fair share)."""
        if tenant and tenant in self._states:
            return tenant
        return DEFAULT_TENANT

    def tenant_for_api_key(self, key: str) -> str | None:
        return self._api_keys.get(key)

    def priority_rank(self, tenant: str | None) -> int:
        """0 = interactive .. 2 = best_effort; preemption victimizes
        the highest rank first."""
        st = self._state(self.resolve(tenant))
        return PRIORITY_CLASSES.index(st.cfg.priority)

    def priority_class(self, tenant: str | None) -> str:
        """The tenant's priority-class NAME ("interactive" / "batch" /
        "best_effort") — the SLO layer's class mapping (a request's
        SLO class is its tenant's priority class)."""
        return self._state(self.resolve(tenant)).cfg.priority

    def weight(self, tenant: str | None) -> float:
        return self._state(self.resolve(tenant)).cfg.weight

    def default_deadline(self, tenant: str | None) -> float | None:
        """The tenant's class-default request deadline in seconds
        (None = unbounded): submit() applies it when the caller passes
        no explicit deadline_s. Plain dict reads on state frozen at
        construction — submit-path hot."""
        return self._class_deadlines.get(
            self._state(self.resolve(tenant)).cfg.priority)

    def header_trusted(self, tenant: str) -> bool:
        """Whether a bare `X-Tenant: <tenant>` header claim is honored
        without an API key: True for unknown names (they collapse to
        the default tenant anyway) and for configured tenants with no
        api_keys; False for key-protected tenants — their identity
        comes only from `tenant_for_api_key`, so a header alone can
        never ride a protected tenant's weight, priority, or rate
        budget."""
        st = self._states.get(tenant)
        return st is None or not st.cfg.api_keys

    def _decay_recent(self, st: _TenantState, now: float) -> None:
        """Decay `st.recent` to `now` (caller holds the lock)."""
        dt = now - st.recent_stamp
        if dt > 0.0:
            st.recent *= 0.5 ** (dt / RECENT_USAGE_HALFLIFE_S)
            st.recent_stamp = now

    def victim_rank(self, tenant: str | None) -> tuple[int, float]:
        """Preemption ordering key for the tenant's slots: (priority
        rank — best_effort highest, RECENT weighted generated-token
        usage — most over fair share first). Usage is the decayed rate
        (RECENT_USAGE_HALFLIFE_S), not the lifetime total, so an
        established tenant's days-old history never shields a current
        flooder. The server takes the MAX of (victim_rank, admit_seq),
        so the full order is (lowest priority class, most over fair
        share, youngest) per docs/serving.md."""
        st = self._state(self.resolve(tenant))
        now = self._clock()
        with self._lock:
            self._decay_recent(st, now)
            return (PRIORITY_CLASSES.index(st.cfg.priority),
                    st.recent / st.cfg.weight)

    # -- submit gate (differentiated backpressure) --------------------------

    def gate_submit(self, tenant: str | None, prompt_tokens: int,
                    charge_tokens: int | None = None) -> None:
        """Admit-or-429 for one submit, called under the server lock
        AFTER the global checks: per-tenant pending bound, then the
        prompt token bucket. On success the tenant's pending count and
        submit counter advance atomically with the queue append the
        caller performs next. A prompt LARGER than the bucket's burst
        capacity could never be admitted no matter how long the client
        waits, so it raises ValueError (HTTP 400, terminal) instead of
        the retryable 429.

        `charge_tokens` overrides how many tokens the prompt bucket is
        billed (default: the full `prompt_tokens`). A migration
        continuation passes 0: the source replica already billed the
        original prompt, and the salvaged generated tokens were never
        prompt tokens — re-billing either would double-charge the
        tenant fleet-wide for one request. The burst-capacity 400
        keys off the same charge: a continuation's already-paid-for
        prompt must never be refused outright."""
        tenant = self.resolve(tenant)
        st = self._state(tenant)
        charge = prompt_tokens if charge_tokens is None else charge_tokens
        if (st.prompt_bucket is not None
                and charge > st.prompt_bucket.burst):
            raise ValueError(
                f"prompt of {charge} tokens exceeds tenant "
                f"{tenant!r}'s burst capacity "
                f"({st.prompt_bucket.burst:g} tokens); no retry can "
                "ever admit it")
        with self._lock:
            bound = st.cfg.max_pending
            if bound is not None and st.pending >= bound:
                st.rejected += 1
                raise TenantQueueFullError(
                    f"tenant {tenant!r} pending queue is full "
                    f"({bound} requests); retry later",
                    tenant=tenant,
                    retry_after_s=self._retry_hint(st, charge))
            if (st.prompt_bucket is not None
                    and not st.prompt_bucket.try_consume(charge)):
                st.rejected += 1
                raise TenantQueueFullError(
                    f"tenant {tenant!r} is over its prompt-token rate "
                    "limit; retry later", tenant=tenant,
                    retry_after_s=st.prompt_bucket.retry_after(
                        charge))
            st.pending += 1
            st.submitted += 1

    def _retry_hint(self, st: _TenantState, prompt_tokens: int) -> float:
        """Retry-After for a pending-bound 429, derived from the
        tenant's bucket refill state (the best host-side guess at when
        capacity frees); 1.0 s when the tenant has no buckets."""
        hints = []
        if st.prompt_bucket is not None:
            hints.append(st.prompt_bucket.retry_after(prompt_tokens))
        if st.generated_bucket is not None:
            hints.append(st.generated_bucket.retry_after(0.0))
        return max(hints) if hints else 1.0

    # -- pending-queue lifecycle -------------------------------------------

    def on_pending_removed(self, tenant: str | None) -> None:
        """A request left the pending queue (admitted into a slot,
        cancelled while queued, or failed)."""
        st = self._state(self.resolve(tenant))
        with self._lock:
            st.pending = max(0, st.pending - 1)

    def on_requeue(self, tenant: str | None) -> None:
        """A preempted request went back to the queue front."""
        st = self._state(self.resolve(tenant))
        with self._lock:
            st.pending += 1
            st.preempt_requeues += 1

    # -- fair-share admission (hot path) ------------------------------------

    def next_admission_index(self, pending) -> int | None:
        """DRR pick over the pending queue: index of the next request
        to admit, or None when the queue is empty. Preserves FIFO
        within each tenant (each tenant's HEAD request is its only
        candidate); tenants in generated-token debt are skipped while
        any other tenant is eligible (work-conserving fallback
        otherwise). Deficits are NOT consumed here — the caller charges
        `charge_admission` once the admission actually succeeds, so a
        page-famine retry next step is not double-billed.

        Cost: the scan EARLY-EXITS once every tenant with queued work
        has shown its head (per-tenant pending counts are maintained
        at submit / requeue / removal under the same server lock this
        runs under), so a single-tenant flood — the overload shape QoS
        exists for — pays O(1) per pick like the FIFO it replaces. A
        deep scan happens only when some tenant's head really is
        buried behind another's flood, i.e. exactly when fairness
        requires digging it out."""
        with self._lock:
            want = sum(1 for st in self._states.values()
                       if st.pending > 0)
        heads: dict[str, tuple[int, int]] = {}
        for i, req in enumerate(pending):
            t = self.resolve(getattr(req, "tenant", None))
            if t not in heads:
                heads[t] = (i, len(req.prompt) + len(req.tokens))
                if want and len(heads) >= want:
                    break
        if not heads:
            return None
        with self._lock:
            for name, st in self._states.items():
                if name not in heads:
                    st.deficit = 0.0  # classic DRR: idle queues hoard
                    #                   nothing across their idle gap
            pool = [t for t in self._order if t in heads]
            eligible = [t for t in pool if self._in_budget(t)]
            if eligible:
                pool = eligible
            # Closed-form DRR: the round-by-round loop ("top everyone
            # up by quantum*weight until someone's deficit covers its
            # head's cost, first in pool order wins") is computed
            # directly — a preempted 100k-token continuation must not
            # cost cost/quantum lock-held scan passes per pick.
            best = rounds = None
            for t in pool:
                st = self._states[t]
                need = heads[t][1] - st.deficit
                r = (0 if need <= 0 else
                     math.ceil(need / (self.quantum * st.cfg.weight)))
                if rounds is None or r < rounds:  # strict: pool-order
                    best, rounds = t, r  # tie-break, like the loop
            if rounds:
                for t in pool:
                    st = self._states[t]
                    st.deficit += rounds * self.quantum * st.cfg.weight
            return heads[best][0]

    def _in_budget(self, tenant: str) -> bool:
        st = self._states[tenant]
        return (st.generated_bucket is None
                or st.generated_bucket.level() >= 0.0)

    def charge_admission(self, tenant: str | None, cost: int) -> None:
        """Consume the admitted request's DRR deficit (prompt cost)."""
        st = self._state(self.resolve(tenant))
        with self._lock:
            st.deficit -= cost

    def order_jobs(self, tenants: list[str | None]) -> list[int]:
        """Weighted-fair order for the admission jobs funding a mixed
        iteration's prefill chunks: job indices sorted by their
        tenant's prefill virtual time (spent-tokens / weight),
        original (FIFO) order within a tenant. Tenants re-entering
        after an idle gap resume at the current virtual time instead
        of replaying their idle credit."""
        names = [self.resolve(t) for t in tenants]
        involved = set(names)
        with self._lock:
            vts = []
            for name in involved:
                st = self._state(name)
                st.prefill_vt = max(st.prefill_vt, self._global_vt)
                vts.append(st.prefill_vt)
            if vts:
                self._global_vt = max(self._global_vt, min(vts))
            return sorted(range(len(names)),
                          key=lambda i: (self._states[names[i]].prefill_vt,
                                         i))

    def charge_prefill(self, tenant: str | None, tokens: int) -> None:
        st = self._state(self.resolve(tenant))
        with self._lock:
            st.prefill_vt += tokens / st.cfg.weight
            st.prefill_tokens += tokens

    # -- accounting (hot path) ----------------------------------------------

    def charge_generated(self, tenant: str | None, n: int = 1) -> None:
        """Bill `n` generated tokens to the tenant: the generated
        bucket takes the debt (deprioritizing future admissions until
        it refills) and the lifetime counter feeds the scrape-path
        mirrors."""
        st = self._state(self.resolve(tenant))
        now = self._clock()
        with self._lock:
            st.generated += n
            self._decay_recent(st, now)
            st.recent += n
            if st.generated_bucket is not None:
                st.generated_bucket.charge(n, now)

    def charge_speculation(self, tenant: str | None, drafted: int,
                           accepted: int) -> None:
        """Account one dispatch's speculative work for the tenant:
        `drafted` tokens were proposed on its rows, `accepted` of them
        committed. The generated-token BUCKET is untouched — committed
        tokens were already billed one by one via charge_generated —
        this only feeds the wasted-speculation ledger (drafted -
        accepted) the scrape-path mirrors and the fleet merge report."""
        st = self._state(self.resolve(tenant))
        with self._lock:
            st.spec_drafted += drafted
            st.spec_accepted += accepted

    # -- scrape-path views --------------------------------------------------

    def tenants(self) -> list[str]:
        return list(self._order)

    def _fair_shares_locked(self) -> dict[str, float]:
        return compute_fair_shares(
            {name: (st.cfg.weight, float(st.generated))
             for name, st in self._states.items()})

    def fair_shares(self) -> dict[str, float]:
        """{tenant: generated-token share / weighted entitlement} —
        1.0 means exactly the fair share; the compact per-iteration
        gauge the paged server's flight recorder records."""
        with self._lock:
            return self._fair_shares_locked()

    def stats(self) -> dict[str, dict]:
        """Per-tenant counters + fair-share view for the metrics
        mirror and /stats. `fair_share` is the tenant's share of all
        generated tokens divided by its weight share — 1.0 means the
        tenant is getting exactly its weighted entitlement."""
        with self._lock:
            shares = self._fair_shares_locked()
            out = {}
            for name, st in self._states.items():
                out[name] = {
                    "weight": st.cfg.weight,
                    "priority": st.cfg.priority,
                    "pending": st.pending,
                    "submitted": st.submitted,
                    "rejected": st.rejected,
                    "generated": st.generated,
                    "preempt_requeues": st.preempt_requeues,
                    "prefill_tokens": st.prefill_tokens,
                    "spec_drafted": st.spec_drafted,
                    "spec_accepted": st.spec_accepted,
                    "spec_wasted": st.spec_drafted - st.spec_accepted,
                    "fair_share": shares[name],
                }
            return out


    def mirror_metrics(self, registry) -> None:
        """Scrape-path mirror of the per-tenant counters into a
        `utils.serving_metrics.MetricsRegistry` as tenant-labeled
        series (one series per tenant per family; the catalog lives in
        docs/observability.md). Called from the servers' snapshot
        collectors — never from the serving hot path."""
        from cloud_server_tpu.utils.serving_metrics import TENANT_TTFT
        for name, s in self.stats().items():
            lbl = {"tenant": name}
            registry.counter(
                "tenant_requests_submitted_total",
                "Requests accepted by submit(), per tenant",
                labels=lbl).set_total(s["submitted"])
            registry.counter(
                "tenant_requests_rejected_total",
                "Per-tenant 429s (pending bound or rate limit)",
                labels=lbl).set_total(s["rejected"])
            registry.counter(
                "tenant_generated_tokens_total",
                "Lifetime generated tokens, per tenant",
                labels=lbl).set_total(s["generated"])
            registry.counter(
                "tenant_prefill_tokens_total",
                "Prefill tokens funded by mixed iterations, per tenant",
                labels=lbl).set_total(s["prefill_tokens"])
            registry.counter(
                "tenant_preempt_requeues_total",
                "Preempt-requeues charged to the tenant's slots",
                labels=lbl).set_total(s["preempt_requeues"])
            registry.counter(
                "tenant_spec_wasted_tokens_total",
                "Rejected speculative draft work on the tenant's rows "
                "(drafted - accepted; committed tokens are billed to "
                "the generated bucket, this is the waste ledger)",
                labels=lbl).set_total(s["spec_wasted"])
            registry.gauge(
                "tenant_pending_requests",
                "Queued requests awaiting admission, per tenant",
                labels=lbl).set(s["pending"])
            registry.gauge(
                "tenant_fair_share",
                "Generated-token share over weighted entitlement "
                "(1.0 = exactly the tenant's fair share)",
                labels=lbl).set(s["fair_share"])
            # eager get-or-create: the TTFT family (observed by
            # ServingMetrics at first token) exists for every known
            # tenant even before its first request
            registry.histogram(*TENANT_TTFT, labels=lbl)


def resolve_registry(qos, qos_config: str = "") -> TenantRegistry | None:
    """The one constructor both servers use: `qos` may be a ready
    TenantRegistry, a config dict, a JSON string, a file path, None
    (falling back to `InferConfig.qos_config`, itself a JSON string or
    path), or the literal False — QoS force-disabled regardless of the
    config fallback (the bench's control arm and any caller that needs
    "explicitly off" rather than "unset"). Returns None — QoS fully
    disabled, byte-identical legacy scheduling — when nothing is
    configured."""
    if qos is False:
        return None
    if isinstance(qos, TenantRegistry):
        return qos
    spec = qos if qos is not None else (qos_config or None)
    if spec is None or spec == "":
        return None
    if isinstance(spec, str):
        text = spec
        if not text.lstrip().startswith("{"):
            with open(text) as f:  # a path, not inline JSON
                text = f.read()
        spec = json.loads(text)
    if not isinstance(spec, dict):
        raise ValueError("qos config must be a JSON object")
    return TenantRegistry(spec)

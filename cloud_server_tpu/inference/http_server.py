"""HTTP front-end for the continuous-batching servers.

A thin stdlib (`http.server`) layer over the server `submit` API —
`PagedInferenceServer` (the recommended backend: paged KV, radix prefix
reuse, chunked prefill, in-server speculative decoding) or the legacy
contiguous `InferenceServer`; both expose the same submit / num_active /
num_pending surface. No framework dependency — the serving hot path
stays the jitted TPU program; this module only does sockets and JSON.

Endpoints:

  POST /generate    (native) {"prompt": "text"} or {"tokens": [...]},
                    optional "max_new_tokens" and any per-request
                    sampling field: temperature, top_k, top_p, min_p,
                    repetition_penalty, presence_penalty,
                    frequency_penalty, seed, ignore_eos, min_tokens,
                    logit_bias ({"token_id": bias}), stop (a string,
                    list of strings, or list of token-id lists).
                    Response is `application/x-ndjson`: one
                    {"token": id, "logprob": lp, "text": s} line per
                    generated token (text only when a tokenizer is
                    attached), then a final {"done": true,
                    "finish_reason": ..., "tokens": [...],
                    "logprobs": [...]}.
  POST /v1/completions        OpenAI-compatible text completion:
                    prompt (string, token list, or list of either),
                    max_tokens, temperature, top_p, stop, seed, n,
                    best_of (candidates ranked by mean token logprob,
                    best n returned), presence_penalty,
                    frequency_penalty, logprobs, response_format
                    ({"type": "json_object"} or {"type": "json_schema",
                    "json_schema": {"schema": ...}} — compiled to a
                    device-side token DFA), stream (SSE chunks, final
                    `data: [DONE]`).
  POST /v1/chat/completions   OpenAI-compatible chat: messages are
                    rendered through the chat template (the attached
                    tokenizer's own, when it has one, else a minimal
                    role-tagged format); same sampling fields; stream
                    sends `chat.completion.chunk` deltas.
  POST /v1/embeddings         mean-pooled, L2-normalised final hidden
                    states for input (string / token list / list of
                    either), OpenAI response shape.
  GET  /v1/models   {"object": "list", "data": [{"id": ...}]}
  GET  /healthz     {"ok": true, "ready": bool, "active": N,
                    "pending": N} — "ok" is liveness; "ready" flips
                    false while the backend is draining (or stopped),
                    so load balancers stop routing here while
                    in-flight work finishes.
  GET  /slo         Per-priority-class SLO attainment + burn rates
                    over the configured rolling windows
                    (inference/slo.py; {"enabled": false} without an
                    SLO config). Behind a ReplicatedRouter the counts
                    merge fleet-wide.
  GET  /autoscaler  The SLO-burn autoscaler's live view (fleet size,
                    burn signal, scale-event tail) when one is
                    attached to the router (scenarios/autoscaler.py);
                    {"enabled": false} otherwise. /stats carries the
                    same block under "autoscaler".
  GET  /debug/requests/<id>  Span tree of one sampled request
                    (inference/request_trace.py): queue / prefill /
                    decode / preempt_gap / emit phases plus
                    iteration-granular scheduler spans cross-linked
                    to the flight recorder. 404 for unknown,
                    unsampled, or evicted ids.
  GET  /traces      Chrome-trace/Perfetto export of the sampled trace
                    ring (?n=K bounds to the newest K trees), plus the
                    tail-retained ring (anomalous head-unsampled
                    requests) and — with a watchdog configured — an
                    `anomalies` marker track carrying each rule
                    window.
  GET  /metrics     Full Prometheus text exposition from the backend's
                    metrics registry: request-lifecycle histograms
                    (TTFT / inter-token / queue-wait / e2e, with
                    buckets), occupancy gauges, lifetime counters,
                    page-pool and prefix-cache stats. Behind a
                    ReplicatedRouter the snapshot is merged across
                    replicas (fleet-wide percentiles). Catalog:
                    docs/observability.md.
  GET  /stats       JSON aggregates (histogram summaries with
                    interpolated percentiles, counters, gauges) plus
                    the scheduler flight recorder's recent window
                    (?n=K bounds the window, default 64) and — with
                    the iteration profiler on (the default) — an
                    `iteration_profile` summary (per-phase
                    count/mean/p50/p99 ms + host_gap_frac) and an
                    `overlap` block (the async double-buffered
                    scheduler's resolved knob state + live pipeline
                    depth). Paged backends add a `cache` block (the
                    /debug/cache payload).
  GET  /debug/scheduler_trace  Chrome-trace/Perfetto export of the
                    flight recorder's recent window (?n=K, default
                    64): one track per scheduler phase (sweep /
                    admission / build / device / commit / launch /
                    epilogue) plus an iteration track carrying each
                    record's scalars, and an `inflight` track whose
                    slices render the async scheduler's
                    launched-ahead dispatches CONCURRENT with the
                    iteration that commits them. Same perf_counter
                    timebase as /traces, and every event tags its
                    flight-recorder iteration index — the two-way
                    cross-link between "this request's decode_segment
                    was slow" and "what the scheduler was doing that
                    iteration" (inference/iteration_profile.py).
  GET  /debug/cache KV-cache & memory observability
                    (inference/cache_telemetry.py): pool occupancy
                    split free/cached/active with the evictable
                    fraction, prefix hit/miss/eviction counts + hit
                    rate, the per-tenant attribution table (hit /
                    miss / saved / evicted tokens, pages held), the
                    hot-prefix top-K sketch, and eviction forensics
                    (recent ring + victim×forcer matrix). Behind a
                    ReplicatedRouter counts sum across replicas and
                    the ratios recompute post-merge. 404 when the
                    backend has no paged KV cache.
  GET  /debug/bundle One-shot forensic debug bundle (JSON,
                    schema "cloud_server.debug_bundle/v1"): metrics
                    snapshot, flight window, iteration profile,
                    head-sampled + tail-retained span trees,
                    cache/SLO/fault/brownout/anomaly state in one
                    artifact (?n=K bounds the ring exports,
                    default 64). ?ring=K instead returns the last K
                    AUTO-captured bundles (snapshotted on anomaly
                    activation when `bundle_on_anomaly` is set).
                    Behind a ReplicatedRouter the bundle is
                    fleet-merged. 404 when the backend has no
                    debug_bundle.
  POST /debug/trace {"steps": N, "logdir": optional} — wrap the next N
                    scheduler iterations in a jax profiler trace
                    (utils.tracing.capture_trace); returns the logdir
                    to point TensorBoard/Perfetto at. An anomaly
                    watchdog configured with capture_iters/capture_dir
                    arms this same machinery automatically when a
                    rule fires.

Streaming text is emitted via incremental decode: each chunk is the
SUFFIX the new tokens added to the decoded string, with a trailing
partial UTF-8 sequence held back until complete (byte-level tokenizers
emit multi-byte characters atomically).

String `stop` entries are tokenized and enforced at token level
(server-side emit rule); with BPE tokenizers a stop string that merges
across a token boundary in the generation may not match — token-id
stops are exact.

Lifecycle: a streaming client that disconnects mid-generation aborts
its request (BrokenPipe -> Request.cancel(); the scheduler frees the
slot and pages within one step). When the backend is constructed with
`max_pending`, submissions past the bound return HTTP 429 — clients
retry instead of growing host memory.

Fault tolerance (docs/serving.md "Fault tolerance"): an
`X-Deadline-S: <seconds>` header sets the request's deadline (the
scheduler cancels it once passed; finish_reason "deadline"); overload
brownout and tenant rate limits both surface as 429s with the
structured `Retry-After` body (brownout hints carry seeded jitter so
shed clients do not thundering-herd the recovery). Behind a
ReplicatedRouter, a request that fails mid-stream is LIVE-MIGRATED
(inference/migration.py): the router salvages its generated state and
resumes it on a healthy replica at the exact next token, on the SAME
stream — the client sees one contiguous token sequence and never
learns a replica died. Only when migration cannot proceed (export
fault, no healthy replica, past deadline) does the stream end with
`{"error", "retriable"}` — `retriable: false` once any token was
streamed (resubmitting from scratch would duplicate delivered output;
the router already exhausted every safe retry AND every migration
path), and non-streaming 503s carry `retriable: true`. Behind a
ReplicatedRouter, `/healthz` gains a `replicas` list with per-replica
circuit-breaker state and `/stats` a fleet-merged `migration` block.

Multi-tenant QoS (inference/qos.py): when the backend carries a
TenantRegistry, each request's tenant comes from an API key
(`Authorization: Bearer <key>` / `X-Api-Key`) the registry maps —
authoritative — or from the `X-Tenant` header, which is trusted only
for tenants that configured no api_keys (a bare header can never
impersonate a key-protected tenant); anonymous requests ride the
implicit default tenant. Every 429 — global bound or per-tenant — is
structured: a `Retry-After` header (seconds, ceil'd) plus a JSON body
`{"error", "retry_after_s", "tenant"}`, where per-tenant rejections
derive `retry_after_s` from the tenant's token-bucket refill. `/stats`
gains a `tenants` section (per-tenant counters + fair-share view) and
`/metrics` the tenant-labeled series cataloged in
docs/observability.md.

Distributed tracing (inference/request_trace.py): when the backend
carries a TraceRecorder, an incoming W3C `traceparent` header joins
the client's trace (its sampled flag is authoritative); responses
that submitted work echo a `traceparent` naming this request's trace
so callers can fetch `/debug/requests/<id>` or stitch downstream
spans. Without a recorder the headers are ignored entirely.

Access logging is OPT-IN (`HttpFrontend(..., access_log=...)`): one
structured JSON line per request (method, path, status, duration,
request id — plus `tenant` and `trace_id` when resolved, correlating
the access log with traces) through utils.logging.JsonLogger; stdlib
http.server plumbing messages route into the same log. Disabled (the
default) nothing is printed — the old unconditional silence, now a
choice.

Demo (server side: `python -m cloud_server_tpu.generate --serve-http
8000 ...` or `HttpFrontend(srv, tok).start()`):

  curl -N -s localhost:8000/v1/chat/completions \
    -d '{"messages": [{"role": "user", "content": "hi"}], "stream": true}'

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(network serving front-end).
"""

from __future__ import annotations

import json
import math
import os
import queue
import tempfile
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from cloud_server_tpu.inference.iteration_profile import (
    profile_summary, scheduler_chrome_trace)
from cloud_server_tpu.inference.request_trace import (
    TRACEPARENT_HEADER, chrome_trace, format_traceparent,
    parse_traceparent)
from cloud_server_tpu.inference.sampling import SamplingParams
from cloud_server_tpu.inference.server import QueueFullError
from cloud_server_tpu.utils.logging import JsonLogger
from cloud_server_tpu.utils.serving_metrics import (
    histogram_summary, render_prometheus)

_STREAM_END = object()

# JSON body field -> SamplingParams field (shared by all POST endpoints;
# OpenAI aliases are folded in by the endpoint parsers)
_SAMPLING_FIELDS = ("temperature", "top_k", "top_p", "min_p",
                    "repetition_penalty", "presence_penalty",
                    "frequency_penalty", "seed", "ignore_eos",
                    "min_tokens", "regex")


def _parse_stop(stop, tokenizer) -> tuple[tuple[int, ...], ...]:
    """OpenAI `stop`: string | [strings] | [[token ids]] -> id tuples."""
    if stop is None:
        return ()
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list):
        raise ValueError('"stop" must be a string or a list')
    out = []
    for s in stop:
        if isinstance(s, str):
            if tokenizer is None:
                raise ValueError(
                    "string stop sequences need a tokenizer; send token-id "
                    "lists instead")
            ids = tokenizer.encode(s)
            if ids:
                out.append(tuple(ids))
        elif (isinstance(s, list)
              and all(isinstance(t, int) for t in s) and s):
            out.append(tuple(s))
        else:
            raise ValueError('"stop" entries must be non-empty strings or '
                             "token-id lists")
    return tuple(out)


def _parse_sampling(body: dict, tokenizer) -> SamplingParams | None:
    """SamplingParams from a JSON body; None when every field is absent
    (keeps the server's zero-overhead default path)."""
    kw = {}
    for f in _SAMPLING_FIELDS:
        if body.get(f) is not None:
            kw[f] = body[f]
    stop = _parse_stop(body.get("stop"), tokenizer)
    if stop:
        kw["stop"] = stop
    bias = body.get("logit_bias")
    if bias:
        if not isinstance(bias, dict):
            raise ValueError('"logit_bias" must be an object mapping '
                             "token ids to biases")
        try:
            kw["logit_bias"] = tuple(
                (int(t), float(b)) for t, b in bias.items())
        except (TypeError, ValueError) as exc:
            raise ValueError(f'bad "logit_bias": {exc}') from exc
    if not kw:
        return None
    try:
        return SamplingParams(**kw)
    except TypeError as exc:  # wrong field types surface as 400s
        raise ValueError(str(exc)) from exc


class _TextStream:
    """Incremental decode: feed token ids, get the newly-stable text
    suffix (holds back a trailing partial UTF-8 sequence)."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        self.ids: list[int] = []
        self.sent = 0

    def feed(self, ids) -> str:
        if self.tokenizer is None:
            return ""
        self.ids.extend(ids)
        text = self.tokenizer.decode(self.ids)
        # hold back trailing replacement chars (partial multi-byte seq)
        stable = len(text)
        while stable > 0 and text[stable - 1] == "�":
            stable -= 1
        delta = text[self.sent:stable]
        self.sent = stable
        return delta

    def flush(self) -> str:
        if self.tokenizer is None:
            return ""
        text = self.tokenizer.decode(self.ids)
        delta = text[self.sent:]
        self.sent = len(text)
        return delta


def _render_chat(messages, tokenizer) -> str:
    """Messages -> prompt text. Uses the tokenizer's own chat template
    when it has one (HF fast tokenizers may); otherwise a minimal
    role-tagged format that is stable across requests (so the radix
    prefix cache hits on shared conversation heads)."""
    tpl = getattr(tokenizer, "apply_chat_template", None)
    if tpl is not None:
        # transformers' apply_chat_template defaults to tokenize=True
        # (returning ids); this function's contract is TEXT
        return tpl(messages, add_generation_prompt=True, tokenize=False)
    parts = []
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content", "")
        if not isinstance(content, str):
            raise ValueError("message content must be a string")
        parts.append(f"<|{role}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


def _finish(reason: str | None) -> str:
    # OpenAI reports "stop" for natural ends (eos or a stop sequence)
    return "length" if reason == "length" else "stop"


def _query_int(url, name: str, default: int | None) -> int | None:
    """Integer query parameter (?n=K), `default` when absent; raises
    ValueError on junk (callers map it to a 400). THE one parser for
    the windowed GET endpoints (/stats, /traces)."""
    raw = parse_qs(url.query).get(name)
    return default if not raw else int(raw[0])


class HttpFrontend:
    """Bind a serving backend (+ optional tokenizer) to an HTTP port.

    `srv` is a `PagedInferenceServer` or `InferenceServer` (any object
    with submit/num_active/num_pending). Its scheduler must be running
    (srv.start()) or be driven externally; this class never steps it.
    """

    def __init__(self, srv, tokenizer=None,
                 host: str = "127.0.0.1", port: int = 0,
                 model_id: str = "cloud-server-tpu",
                 access_log: bool | str | os.PathLike | JsonLogger
                 | None = None):
        self.srv = srv
        self.tokenizer = tokenizer
        self.model_id = model_id
        # opt-in structured access log: True -> JSON lines on stderr,
        # a path -> JSONL file, or a ready JsonLogger-like object
        self._owns_log = access_log is True or isinstance(
            access_log, (str, os.PathLike))
        if access_log is True:
            self.access_log = JsonLogger()
        elif isinstance(access_log, (str, os.PathLike)):
            self.access_log = JsonLogger(path=access_log)
        else:
            self.access_log = access_log or None
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                # stdlib plumbing (errors, odd requests): routed into
                # the structured log when enabled, silent otherwise
                if front.access_log is not None:
                    front.access_log.log({"event": "http_log",
                                          "message": fmt % args})

            def send_response(self, code, message=None):
                self._status = code  # remembered for the access record
                super().send_response(code, message)

            def _access(self, method: str, t0: float) -> None:
                if front.access_log is None:
                    return
                record = {
                    "event": "access", "method": method,
                    "path": self.path,
                    "status": getattr(self, "_status", None),
                    "duration_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3),
                    "request_id": getattr(self, "_rid", None)}
                # trace/tenant correlation: present only when resolved
                # for this request, so untraced deployments' log shape
                # is unchanged
                tenant = getattr(self, "_tenant", None)
                if tenant:
                    record["tenant"] = tenant
                trace_id = getattr(self, "_trace_id", None)
                if trace_id:
                    record["trace_id"] = trace_id
                front.access_log.log(record)

            def _begin(self) -> float:
                self._rid = (self.headers.get("X-Request-Id")
                             or uuid.uuid4().hex[:12])
                self._status = None
                self._tenant = None
                self._trace_ctx = None
                self._trace_id = None
                self._deadline_s = None
                return time.perf_counter()

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                t0 = self._begin()
                try:
                    self._do_get()
                finally:
                    self._access("GET", t0)

            def _do_get(self):
                url = urlparse(self.path)
                if url.path == "/healthz":
                    # ok = liveness; ready = routability (false while
                    # the backend drains or after stop(), so load
                    # balancers shed this replica without killing its
                    # in-flight work). Behind a ReplicatedRouter the
                    # payload gains per-replica circuit-breaker state.
                    payload = {"ok": True,
                               "ready": bool(getattr(
                                   front.srv, "ready", True)),
                               "active": front.srv.num_active,
                               "pending": front.srv.num_pending}
                    bfn = getattr(front.srv, "breaker_states", None)
                    if bfn is not None:
                        payload["replicas"] = bfn()
                    self._json(200, payload)
                elif url.path == "/slo":
                    fn = getattr(front.srv, "slo_report", None)
                    rep = fn() if fn is not None else None
                    self._json(200, rep if rep is not None
                               else {"enabled": False})
                elif url.path == "/autoscaler":
                    # scenario-harness hook: the SLO-burn autoscaler's
                    # live view (scenarios/autoscaler.py attaches it
                    # to the router it scales)
                    asc = getattr(front.srv, "autoscaler", None)
                    self._json(200, asc.stats() if asc is not None
                               else {"enabled": False})
                elif url.path == "/traces":
                    fn = getattr(front.srv, "trace_trees", None)
                    if fn is None:
                        self._json(404, {"error": "this serving backend "
                                         "does not support tracing"})
                        return
                    try:
                        n = _query_int(url, "n", None)
                    except ValueError:
                        self._json(400, {"error": '"n" must be an int'})
                        return
                    trees = fn(n)
                    # tail-retained trees join the export (disjoint
                    # from head-sampled by construction); anomaly
                    # windows become a Perfetto marker track
                    tfn = getattr(front.srv, "tail_trace_trees", None)
                    if tfn is not None:
                        trees = trees + tfn(n)
                    afn = getattr(front.srv, "anomaly_events", None)
                    self._json(200, chrome_trace(
                        trees,
                        anomalies=afn(n) if afn is not None else None))
                elif url.path.startswith("/debug/requests/"):
                    rid = url.path[len("/debug/requests/"):]
                    fn = getattr(front.srv, "lookup_trace", None)
                    tree = fn(rid) if fn is not None and rid else None
                    if tree is None:
                        self._json(404, {
                            "error": "unknown, unsampled, or evicted "
                            "request id (tracing must be enabled and "
                            "the request sampled)"})
                    else:
                        self._json(200, tree)
                elif url.path == "/debug/cache":
                    fn = getattr(front.srv, "cache_stats", None)
                    if fn is None:
                        self._json(404, {"error": "this serving "
                                         "backend has no paged KV "
                                         "cache"})
                        return
                    self._json(200, fn())
                elif url.path == "/debug/scheduler_trace":
                    fn = getattr(front.srv, "flight_window", None)
                    if fn is None:
                        self._json(404, {"error": "this serving backend "
                                         "has no flight recorder"})
                        return
                    try:
                        n = _query_int(url, "n", 64)
                    except ValueError:
                        self._json(400, {"error": '"n" must be an int'})
                        return
                    self._json(200, scheduler_chrome_trace(
                        fn(n) if n > 0 else []))
                elif url.path == "/debug/bundle":
                    fn = getattr(front.srv, "debug_bundle", None)
                    if fn is None:
                        self._json(404, {"error": "this serving "
                                         "backend has no debug "
                                         "bundles"})
                        return
                    try:
                        n = _query_int(url, "n", 64)
                        ring = _query_int(url, "ring", 0)
                    except ValueError:
                        self._json(400, {"error": '"n" and "ring" '
                                         'must be ints'})
                        return
                    if ring:
                        # ?ring=k: the last k AUTO-captured bundles
                        # (anomaly snapshots) instead of a fresh one
                        self._json(200, {"bundles":
                                         front.srv.debug_bundles(ring)})
                    else:
                        self._json(200, fn(n))
                elif url.path == "/metrics":
                    body = front._metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif url.path == "/stats":
                    try:
                        n = _query_int(url, "n", 64)
                    except ValueError:
                        self._json(400, {"error": '"n" must be an int'})
                        return
                    self._json(200, front._stats_json(n))
                elif url.path == "/v1/models":
                    models = [{"id": front.model_id, "object": "model",
                               "owned_by": "cloud-server-tpu"}]
                    adapters = getattr(front.srv, "adapters", None)
                    if adapters is not None:
                        models += [{"id": n, "object": "model",
                                    "owned_by": "cloud-server-tpu",
                                    "parent": front.model_id}
                                   for n in adapters.names]
                    self._json(200, {"object": "list", "data": models})
                else:
                    self._json(404, {"error": "unknown path"})

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                return body

            def do_POST(self):
                t0 = self._begin()
                try:
                    self._do_post()
                finally:
                    self._access("POST", t0)

            def _do_post(self):
                routes = {"/generate": front._handle_generate,
                          "/v1/completions": front._handle_completions,
                          "/v1/chat/completions": front._handle_chat,
                          "/v1/embeddings": front._handle_embeddings,
                          "/debug/trace": front._handle_debug_trace}
                handler = routes.get(self.path)
                if handler is None:
                    self._json(404, {"error": "unknown path"})
                    return
                # multi-tenant QoS: tenant identity rides on headers
                # (X-Tenant, or an API key the registry maps), resolved
                # once per request and threaded into every submit
                self._tenant = front._resolve_tenant(self.headers)
                # distributed tracing: a W3C traceparent joins the
                # caller's trace (parsed once; malformed headers
                # degrade to a fresh trace, never an error)
                self._trace_ctx = parse_traceparent(
                    self.headers.get(TRACEPARENT_HEADER))
                if self._trace_ctx is not None:
                    self._trace_id = self._trace_ctx[0]
                try:
                    body = self._body()
                except (ValueError, json.JSONDecodeError) as exc:
                    self._json(400, {"error": str(exc)})
                    return
                # request deadline: X-Deadline-S seconds from now; the
                # scheduler sweep cancels the request once it passes
                # and the router stops failover retries past it.
                # Validated AFTER the body read: this handler speaks
                # HTTP/1.1 keep-alive, and a 400 sent with the body
                # unconsumed would desync the next request on the
                # connection. `not (x > 0)` so NaN (False both ways)
                # cannot slip through as a never-expiring deadline.
                raw_dl = self.headers.get("X-Deadline-S")
                if raw_dl is not None:
                    try:
                        dl = float(raw_dl)
                        if not (math.isfinite(dl) and dl > 0):
                            raise ValueError
                        self._deadline_s = dl
                    except ValueError:
                        self._json(400, {
                            "error": "X-Deadline-S must be a finite "
                            "positive number of seconds"})
                        return
                try:
                    handler(self, body)
                except (ValueError, TypeError, KeyError,
                        AttributeError) as exc:
                    # type-confused bodies (e.g. {"prompt": 123},
                    # non-object messages) surface wherever they break —
                    # all are client errors, never handler-thread crashes
                    self._json(400, {"error": str(exc)})
                except QueueFullError as exc:  # backpressure, retryable
                    # structured 429: clients get machine-readable retry
                    # guidance instead of a bare string. Per-tenant
                    # rejections (TenantQueueFullError) carry the
                    # tenant's token-bucket refill estimate; the global
                    # bound falls back to a 1 s hint.
                    retry = float(getattr(exc, "retry_after_s", 1.0))
                    self._json(
                        429,
                        {"error": str(exc),
                         "retry_after_s": round(retry, 3),
                         "tenant": getattr(exc, "tenant", self._tenant)},
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry)))})
                except RuntimeError as exc:  # scheduler stopped/crashed
                    # retriable: true — nothing was delivered to this
                    # client (streaming failures surface in-stream with
                    # their own retriable flag), so resubmission is
                    # safe once a replica recovers
                    self._json(503, {"error": str(exc),
                                     "retriable": True})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    # -- shared plumbing ----------------------------------------------------

    def _snapshot(self) -> dict:
        """The backend's registry snapshot: a server's own, or (behind
        ReplicatedRouter) the fleet-wide merge. The names are the
        `cloud_server_` catalog in docs/observability.md (drift-checked
        by tests/test_observability.py)."""
        fn = getattr(self.srv, "metrics_snapshot", None)
        return fn() if fn is not None else {}

    def _metrics_text(self) -> str:
        """Full Prometheus text exposition (HELP/TYPE per series,
        histogram buckets with `le` labels plus _sum/_count)."""
        return render_prometheus(self._snapshot())

    def _stats_json(self, n: int) -> dict:
        """The /stats payload: histogram summaries (count / mean /
        interpolated p50/p95/p99), raw counters and gauges, and — when
        the backend has a flight recorder — its last `n` per-iteration
        records (token-budget utilization, prefill/decode split,
        occupancy, compaction, preemptions)."""
        snap = self._snapshot()
        payload = {
            "active": self.srv.num_active,
            "pending": self.srv.num_pending,
            "latency": {name: histogram_summary(entry)
                        for name, entry in snap.items()
                        if entry["type"] == "histogram"},
            "counters": {name: entry["value"]
                         for name, entry in snap.items()
                         if entry["type"] == "counter"},
            "gauges": {name: entry["value"]
                       for name, entry in snap.items()
                       if entry["type"] == "gauge"},
        }
        fn = getattr(self.srv, "flight_window", None)
        if fn is not None:
            # n bounds the window; n <= 0 means "no records", never
            # "everything" (256+ per-iteration dicts)
            payload["flight_recorder"] = fn(n) if n > 0 else []
        # iteration-phase profile: per-phase p50/p99 + host_gap_frac,
        # computed from the snapshot already in hand — behind the
        # router that snapshot is the fleet merge, so the percentiles
        # are fleet-wide for free. Absent when profiling is disabled.
        profile = profile_summary(snap)
        if profile is not None:
            payload["iteration_profile"] = profile
        # KV-cache & memory: pool occupancy, prefix hit rate,
        # per-tenant attribution, the hot-prefix sketch, and eviction
        # forensics (cache_telemetry.py). Behind the router the counts
        # are fleet-merged with ratios recomputed post-merge.
        cfn = getattr(self.srv, "cache_stats", None)
        if cfn is not None:
            payload["cache"] = cfn()
        # async double-buffered scheduler: the knob's resolved state
        # and the live pipeline depth (single-server debug view; the
        # per-iteration overlap fields ride in flight_recorder records
        # and the folded `overlap` phase in iteration_profile)
        ofn = getattr(self.srv, "overlap_stats", None)
        if ofn is not None:
            payload["overlap"] = ofn()
        # speculative decoding: drafted/accepted totals, the accept
        # rate, and (adaptive) the live per-slot draft lengths.
        # ReplicatedRouter's speculation_stats() merges counts across
        # replicas and recomputes the rate from the merged totals.
        sfn = getattr(self.srv, "speculation_stats", None)
        if sfn is not None:
            payload["speculation"] = sfn()
        # failure-domain blocks: brownout level/signals and injected-
        # fault counts, present only when configured (single-server
        # debug views; the COUNTERS merge fleet-wide via /metrics)
        bofn = getattr(self.srv, "brownout_stats", None)
        if bofn is not None:
            bstats = bofn()
            if bstats is not None:
                payload["brownout"] = bstats
        ffn = getattr(self.srv, "fault_stats", None)
        if ffn is not None:
            fstats = ffn()
            if fstats is not None:
                payload["faults"] = fstats
        # anomaly watchdog (active windows, per-rule fire counts, the
        # bounded event ring) + tail-retention accounting, present
        # only when configured. Behind the router the anomaly block
        # is the fleet merge (merge_anomaly_stats).
        afn = getattr(self.srv, "anomaly_stats", None)
        if afn is not None:
            astats = afn()
            if astats is not None:
                payload["anomaly"] = astats
        ttfn = getattr(self.srv, "tail_trace_stats", None)
        if ttfn is not None:
            ttstats = ttfn()
            if ttstats is not None:
                payload["tail_retention"] = ttstats
        # live-migration counters (inference/migration.py): behind the
        # router this is the fleet merge with success_rate recomputed
        # from the merged totals; a single server reports its ledger
        mfn = getattr(self.srv, "migration_stats", None)
        if mfn is not None:
            mstats = mfn()
            if mstats is not None:
                payload["migration"] = mstats
        # router breaker view (behind a ReplicatedRouter)
        brfn = getattr(self.srv, "breaker_states", None)
        if brfn is not None:
            payload["breakers"] = brfn()
        # SLO-burn autoscaler (scenarios/autoscaler.py attaches itself
        # to the router): fleet size, burn signal, scale-event tail
        asc = getattr(self.srv, "autoscaler", None)
        if asc is not None:
            payload["autoscaler"] = asc.stats()
        # replica role map (disaggregated prefill/decode fleets; all
        # "colocated" when no roles are configured)
        rfn = getattr(self.srv, "replica_roles", None)
        if rfn is not None:
            payload["roles"] = rfn()
        # multi-tenant QoS: per-tenant counters + fair-share view.
        # ReplicatedRouter merges these across replicas
        # (tenant_stats()); a single server reports its registry's.
        tfn = getattr(self.srv, "tenant_stats", None)
        if tfn is not None:
            tstats = tfn()
            if tstats:
                payload["tenants"] = tstats
        else:
            reg = getattr(self.srv, "qos", None)
            if reg is not None:
                payload["tenants"] = reg.stats()
        return payload

    def _handle_debug_trace(self, handler, body: dict) -> None:
        """POST /debug/trace: wrap the next N scheduler iterations in a
        jax profiler trace. Body: {"steps": N (default 1), "logdir":
        path (default a fresh tempdir)}; the response echoes the logdir
        to open in TensorBoard/Perfetto."""
        fn = getattr(self.srv, "request_trace", None)
        if fn is None:
            raise ValueError(
                "this serving backend does not support trace capture")
        steps = body.get("steps", 1)
        if not isinstance(steps, int) or steps <= 0:
            raise ValueError('"steps" must be a positive int')
        logdir = body.get("logdir")
        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="cloud-server-trace-")
        elif not isinstance(logdir, str):
            raise ValueError('"logdir" must be a string path')
        fn(steps, logdir)
        handler._json(200, {"ok": True, "steps": steps,
                            "logdir": logdir})

    def _encode(self, req: dict) -> list[int]:
        if "tokens" in req:
            tokens = req["tokens"]
            if (not isinstance(tokens, list)
                    or not all(isinstance(t, int) for t in tokens)):
                raise ValueError('"tokens" must be a list of ints')
            return tokens
        if "prompt" in req:
            if self.tokenizer is None:
                raise ValueError(
                    'no tokenizer attached; send {"tokens": [...]} instead')
            return self.tokenizer.encode(req["prompt"]) or [0]
        raise ValueError('body needs "prompt" or "tokens"')

    def _resolve_tenant(self, headers) -> str | None:
        """Tenant identity for one request. An API key
        (`Authorization: Bearer <key>` or `X-Api-Key`) the backend's
        TenantRegistry maps is AUTHORITATIVE; the spoofable `X-Tenant`
        header is honored only for tenants that configured no api_keys
        (`TenantRegistry.header_trusted`) — claiming a key-protected
        tenant without its key falls through to anonymous/default
        instead of riding the protected tenant's weight and budget.
        With QoS disabled (no registry) every request is anonymous:
        an attacker-chosen header value must never become a metric
        label (unbounded per-tenant histogram cardinality) — only a
        registry's frozen tenant set bounds that. None resolves to the
        implicit default tenant server-side."""
        reg = getattr(self.srv, "qos", None)
        if reg is None:
            return None
        auth = headers.get("Authorization", "")
        # RFC 7235: the auth scheme is case-insensitive
        key = (auth[7:].strip() if auth[:7].lower() == "bearer "
               else headers.get("X-Api-Key"))
        if key:
            mapped = reg.tenant_for_api_key(key)
            if mapped:
                return mapped
        t = (headers.get("X-Tenant") or "").strip()
        if t and reg.header_trusted(t):
            return t
        return None

    @staticmethod
    def _tenant_kw(handler) -> dict:
        """submit() kwargs carrying the handler's resolved tenant —
        empty when anonymous, so backends without a `tenant` parameter
        (third-party submit surfaces) keep working untouched."""
        t = getattr(handler, "_tenant", None)
        return {"tenant": t} if t else {}

    @staticmethod
    def _trace_kw(handler) -> dict:
        """submit() kwargs carrying the parsed incoming traceparent —
        empty when the client sent none (same third-party-backend rule
        as _tenant_kw; local head sampling still applies either way)."""
        ctx = getattr(handler, "_trace_ctx", None)
        return {"trace_ctx": ctx} if ctx is not None else {}

    @staticmethod
    def _deadline_kw(handler) -> dict:
        """submit() kwargs carrying the parsed X-Deadline-S header —
        empty when the client sent none (same third-party-backend
        rule as _tenant_kw)."""
        dl = getattr(handler, "_deadline_s", None)
        return {"deadline_s": dl} if dl is not None else {}

    @staticmethod
    def _error_line(request) -> dict | None:
        """Structured terminal error for a STREAMING response whose
        request failed: `{"error", "retriable"}`. retriable is False
        once any token was streamed — the client must not resubmit or
        it may receive duplicated output. Behind a ReplicatedRouter
        this surfaces only for NON-MIGRATABLE failures: the router
        first exhausts every zero-token retry AND every live-migration
        path (inference/migration.py — a migrated request continues on
        the same stream and never reaches here). None when the request
        did not fail."""
        reason = request.finish_reason or ""
        if not reason.startswith("error"):
            return None
        return {"error": reason, "retriable": not request.tokens}

    @staticmethod
    def _trace_headers(handler, request) -> dict:
        """Response headers for a submitted request: a W3C
        `traceparent` naming its trace (so the caller can stitch
        downstream spans or fetch /debug/requests/<id>), empty when
        the request was not sampled. Also notes the trace id for the
        access log."""
        tr = getattr(request, "trace", None)
        if tr is None:
            return {}
        handler._trace_id = tr.trace_id
        return {TRACEPARENT_HEADER: format_traceparent(
            tr.trace_id, tr.root_span_id)}

    def _adapter_kw(self, body: dict) -> dict:
        """OpenAI routing: a `model` naming a registered LoRA adapter
        selects it (vLLM convention); the base model id or an unknown
        name selects the base model."""
        name = body.get("model")
        adapters = getattr(self.srv, "adapters", None)
        if (isinstance(name, str) and adapters is not None
                and adapters.adapter_id(name) is not None):
            return {"adapter": name}
        return {}

    def _submit_streaming(self, tokens, max_new, sampling, **kw):
        """Submit with a queue-backed stream; returns (request, queue).
        The queue yields token ids then _STREAM_END."""
        q: queue.Queue = queue.Queue()
        request = self.srv.submit(tokens, max_new_tokens=max_new,
                                  stream=q.put, sampling=sampling, **kw)
        threading.Thread(  # unblock q.get when generation ends
            target=lambda: (request._done.wait(), q.put(_STREAM_END)),
            daemon=True).start()
        return request, q

    @staticmethod
    def _drain(q):
        while True:
            tok = q.get()
            if tok is _STREAM_END:
                return
            yield int(tok)

    # -- native endpoint ----------------------------------------------------

    def _handle_generate(self, handler, body: dict) -> None:
        max_new = body.get("max_new_tokens")
        if max_new is not None and not isinstance(max_new, int):
            raise ValueError('"max_new_tokens" must be an int')
        tokens = self._encode(body)
        sampling = _parse_sampling(body, self.tokenizer)
        kw = {}
        if body.get("adapter") is not None:
            if getattr(self.srv, "adapters", None) is None:
                raise ValueError(
                    "this serving backend does not support adapters")
            kw["adapter"] = body["adapter"]
        kw.update(self._tenant_kw(handler))
        kw.update(self._trace_kw(handler))
        kw.update(self._deadline_kw(handler))
        request, q = self._submit_streaming(tokens, max_new, sampling,
                                            **kw)

        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Connection", "close")
        for k, v in self._trace_headers(handler, request).items():
            handler.send_header(k, v)
        handler.end_headers()
        emitted = 0
        try:
            for tok in self._drain(q):
                line = {"token": tok}
                # _emit appends the logprob before invoking the stream
                # callback, so it is present by the time we get here
                if emitted < len(request.logprobs):
                    line["logprob"] = request.logprobs[emitted]
                emitted += 1
                if self.tokenizer is not None:
                    line["text"] = self.tokenizer.decode([tok])
                handler.wfile.write((json.dumps(line) + "\n").encode())
                handler.wfile.flush()
            err = self._error_line(request)
            if err is not None:
                # structured terminal error: a partially-streamed
                # request that could NOT be live-migrated ends with
                # retriable: false (resending would duplicate the
                # streamed tokens); zero-token failures are safe to
                # resubmit
                handler.wfile.write((json.dumps(err) + "\n").encode())
            else:
                handler.wfile.write((json.dumps(
                    {"done": True,
                     "finish_reason": request.finish_reason,
                     "tokens": request.tokens,
                     "logprobs": request.logprobs}) + "\n").encode())
        except (BrokenPipeError, ConnectionResetError):
            # the client went away: stop generating on its behalf — the
            # scheduler frees the slot and pages within one step
            request.cancel()

    # -- OpenAI-compatible endpoints ----------------------------------------

    def _openai_sampling(self, body: dict):
        """(max_tokens, SamplingParams) with OpenAI aliases folded in:
        max_tokens; response_format {"type": "json_object"} -> the
        canned bounded-depth JSON grammar; response_format
        {"type": "json_schema", "json_schema": {"schema": {...}}} ->
        the schema compiled through json_schema_regex (closed objects,
        declared key order — OpenAI structured-output semantics)."""
        max_new = body.get("max_tokens", body.get("max_new_tokens"))
        if max_new is not None and not isinstance(max_new, int):
            raise ValueError('"max_tokens" must be an int')
        rf = body.get("response_format")
        if isinstance(rf, dict) and rf.get("type") == "json_object":
            from cloud_server_tpu.inference.grammar import \
                json_object_regex
            body = dict(body)
            body.setdefault("regex", json_object_regex())
        elif isinstance(rf, dict) and rf.get("type") == "json_schema":
            from cloud_server_tpu.inference.grammar import \
                json_schema_regex
            wrapper = rf.get("json_schema")
            if not isinstance(wrapper, dict):
                raise ValueError('response_format json_schema needs a '
                                 '"json_schema" object')
            schema = wrapper.get("schema")
            if schema is None:  # accept a bare schema in place of the
                # OpenAI {"name", "schema"} wrapper, but not junk
                looks = ("type", "properties", "enum", "const", "anyOf",
                         "oneOf")
                if not any(k in wrapper for k in looks):
                    raise ValueError(
                        'response_format json_schema needs a "schema"')
                schema = wrapper
            body = dict(body)
            body.setdefault("regex", json_schema_regex(schema))
        return max_new, _parse_sampling(body, self.tokenizer)

    def _prompt_variants(self, body: dict) -> list[list[int]]:
        """OpenAI `prompt`: string | token list | list of either."""
        prompt = body.get("prompt")
        if prompt is None:
            raise ValueError('body needs "prompt"')
        if isinstance(prompt, str):
            prompts = [prompt]
        elif isinstance(prompt, list) and prompt and all(
                isinstance(t, int) for t in prompt):
            prompts = [prompt]
        elif isinstance(prompt, list) and prompt:
            prompts = prompt
        else:
            raise ValueError('"prompt" must be a string, a token list, or '
                             "a non-empty list of those")
        out = []
        for p in prompts:
            if isinstance(p, str):
                if self.tokenizer is None:
                    raise ValueError("no tokenizer attached; send token "
                                     "lists instead")
                out.append(self.tokenizer.encode(p) or [0])
            elif isinstance(p, list) and all(
                    isinstance(t, int) for t in p):
                out.append(p)
            else:
                raise ValueError('"prompt" entries must be strings or '
                                 "token-id lists")
        return out

    def _sse_head(self, handler, headers: dict | None = None) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.end_headers()

    @staticmethod
    def _sse(handler, payload) -> None:
        handler.wfile.write(f"data: {json.dumps(payload)}\n\n".encode())
        handler.wfile.flush()

    def _handle_completions(self, handler, body: dict) -> None:
        max_new, sampling = self._openai_sampling(body)
        prompts = self._prompt_variants(body)
        n = body.get("n", 1)
        if not isinstance(n, int) or n < 1:
            raise ValueError('"n" must be a positive int')
        best_of = body.get("best_of", n)
        if not isinstance(best_of, int) or best_of < n:
            raise ValueError('"best_of" must be an int >= n')
        if best_of > 20:  # OpenAI's own cap; bounds the fan-out
            raise ValueError('"best_of" must be <= 20')
        if best_of > n and body.get("stream"):
            raise ValueError('"best_of" cannot be used with streaming')
        want_logprobs = body.get("logprobs") is not None
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        base = {"id": rid, "object": "text_completion", "created": created,
                "model": body.get("model", self.model_id)}

        if body.get("stream"):
            if len(prompts) > 1 or n > 1:
                raise ValueError("streaming supports a single prompt with "
                                 "n=1")
            request, q = self._submit_streaming(
                prompts[0], max_new, sampling,
                **self._adapter_kw(body), **self._tenant_kw(handler),
                **self._trace_kw(handler), **self._deadline_kw(handler))
            self._sse_head(handler,
                           self._trace_headers(handler, request))
            stream = _TextStream(self.tokenizer)
            try:
                for tok in self._drain(q):
                    delta = stream.feed([tok])
                    if delta:
                        self._sse(handler, {
                            **base,
                            "choices": [{"text": delta, "index": 0,
                                         "logprobs": None,
                                         "finish_reason": None}]})
                err = self._error_line(request)
                if err is not None:
                    self._sse(handler, {**base, **err})
                else:
                    tail = stream.flush()
                    choice = {"text": tail, "index": 0,
                              "logprobs": None,
                              "finish_reason":
                                  _finish(request.finish_reason)}
                    self._sse(handler, {**base, "choices": [choice]})
                handler.wfile.write(b"data: [DONE]\n\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                request.cancel()  # client disconnected mid-stream
            return

        def choice_sampling(k: int):
            # multiple candidates with an explicit seed must still be
            # DISTINCT samples: derive per-candidate seeds
            if (best_of > 1 and sampling is not None
                    and sampling.seed is not None):
                import dataclasses as _dc
                return _dc.replace(
                    sampling, seed=(sampling.seed + k) % (2 ** 32))
            return sampling

        akw = {**self._adapter_kw(body), **self._tenant_kw(handler),
               **self._trace_kw(handler), **self._deadline_kw(handler)}
        cands, submitted = [], []
        try:
            for p in prompts:
                cands.append([])
                for k in range(best_of):
                    r = self.srv.submit(p, max_new_tokens=max_new,
                                        sampling=choice_sampling(k),
                                        **akw)
                    cands[-1].append(r)
                    submitted.append(r)
        except Exception:
            # a mid-fan-out failure (e.g. QueueFullError) must not
            # leave the earlier candidates decoding for no one
            for r in submitted:
                r.cancel()
            raise
        try:
            for group in cands:
                for r in group:
                    r.result()
        except Exception:
            for r in submitted:  # same rule for mid-GENERATION failure
                r.cancel()
            raise
        if best_of > n:
            # OpenAI best_of: rank the candidates by mean token logprob
            # (the model's own raw distribution) and return the best n
            def mean_lp(r):
                return (sum(r.logprobs) / len(r.logprobs)
                        if r.logprobs else float("-inf"))

            cands = [sorted(group, key=mean_lp, reverse=True)[:n]
                     for group in cands]
        reqs = [r for group in cands for r in group]
        choices = []
        # OpenAI usage semantics: EVERY best_of candidate's completion
        # tokens count (they were all generated); the prompt counts
        # ONCE per prompt, not per candidate
        usage_p = sum(len(p) for p in prompts)
        usage_c = sum(len(r.tokens) for r in submitted)
        for i, r in enumerate(reqs):
            toks = r.result()
            choice = {
                "text": (self.tokenizer.decode(toks)
                         if self.tokenizer is not None else ""),
                "index": i, "logprobs": None,
                "finish_reason": _finish(r.finish_reason)}
            if want_logprobs:
                choice["logprobs"] = {
                    "tokens": [self.tokenizer.decode([t])
                               if self.tokenizer is not None else str(t)
                               for t in toks],
                    "token_logprobs": r.logprobs,
                    "top_logprobs": None, "text_offset": None}
            if self.tokenizer is None:
                choice["tokens"] = toks  # still useful without text
            choices.append(choice)
        handler._json(200, {
            **base, "choices": choices,
            "usage": {"prompt_tokens": usage_p,
                      "completion_tokens": usage_c,
                      "total_tokens": usage_p + usage_c}},
            headers=self._trace_headers(handler, submitted[0]))

    def _handle_embeddings(self, handler, body: dict) -> None:
        """OpenAI /v1/embeddings: input is a string, a token list, or a
        list of either; vectors are the backend's mean-pooled
        L2-normalised final hidden states."""
        embed_fn = getattr(self.srv, "embed", None)
        if embed_fn is None:
            raise ValueError(
                "this serving backend does not support embeddings")
        raw = body.get("input")
        if raw is None:
            raise ValueError('body needs "input"')
        if isinstance(raw, str) or (
                isinstance(raw, list) and raw
                and all(isinstance(t, int) for t in raw)):
            raw = [raw]
        if not isinstance(raw, list) or not raw:
            raise ValueError('"input" must be a string, a token list, '
                             "or a non-empty list of those")
        token_lists = []
        for item in raw:
            if isinstance(item, str):
                if self.tokenizer is None:
                    raise ValueError("no tokenizer attached; send token "
                                     "lists instead")
                token_lists.append(self.tokenizer.encode(item) or [0])
            elif (isinstance(item, list) and item
                  and all(isinstance(t, int) for t in item)):
                token_lists.append(item)
            else:
                raise ValueError('"input" entries must be non-empty '
                                 "strings or token-id lists")
        vecs = embed_fn(token_lists)
        handler._json(200, {
            "object": "list",
            "model": body.get("model", self.model_id),
            "data": [{"object": "embedding", "index": i,
                      "embedding": [float(x) for x in v]}
                     for i, v in enumerate(vecs)],
            "usage": {"prompt_tokens": sum(map(len, token_lists)),
                      "total_tokens": sum(map(len, token_lists))}})

    def _handle_chat(self, handler, body: dict) -> None:
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError('"messages" must be a non-empty list')
        if self.tokenizer is None:
            raise ValueError("chat completions need a tokenizer")
        max_new, sampling = self._openai_sampling(body)
        prompt = self.tokenizer.encode(
            _render_chat(messages, self.tokenizer)) or [0]
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        base = {"id": rid, "created": created,
                "model": body.get("model", self.model_id)}

        if body.get("stream"):
            request, q = self._submit_streaming(
                prompt, max_new, sampling,
                **self._adapter_kw(body), **self._tenant_kw(handler),
                **self._trace_kw(handler), **self._deadline_kw(handler))
            self._sse_head(handler,
                           self._trace_headers(handler, request))
            stream = _TextStream(self.tokenizer)
            try:
                self._sse(handler, {
                    **base, "object": "chat.completion.chunk",
                    "choices": [{"index": 0,
                                 "delta": {"role": "assistant"},
                                 "finish_reason": None}]})
                for tok in self._drain(q):
                    delta = stream.feed([tok])
                    if delta:
                        self._sse(handler, {
                            **base, "object": "chat.completion.chunk",
                            "choices": [{"index": 0,
                                         "delta": {"content": delta},
                                         "finish_reason": None}]})
                err = self._error_line(request)
                if err is not None:
                    self._sse(handler, {**base, **err})
                else:
                    tail = stream.flush()
                    delta = {"content": tail} if tail else {}
                    self._sse(handler, {
                        **base, "object": "chat.completion.chunk",
                        "choices": [{
                            "index": 0, "delta": delta,
                            "finish_reason":
                                _finish(request.finish_reason)}]})
                handler.wfile.write(b"data: [DONE]\n\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                request.cancel()  # client disconnected mid-stream
            return

        req = self.srv.submit(prompt, max_new_tokens=max_new,
                              sampling=sampling,
                              **self._adapter_kw(body),
                              **self._tenant_kw(handler),
                              **self._trace_kw(handler),
                              **self._deadline_kw(handler))
        toks = req.result()
        handler._json(200, {
            **base, "object": "chat.completion",
            "choices": [{
                "index": 0,
                "message": {"role": "assistant",
                            "content": self.tokenizer.decode(toks)},
                "finish_reason": _finish(req.finish_reason)}],
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": len(toks),
                      "total_tokens": len(prompt) + len(toks)}},
            headers=self._trace_headers(handler, req))

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="http-frontend")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._owns_log and self.access_log is not None:
            self.access_log.close()
            self.access_log = None

"""HTTP front-end for the continuous-batching servers.

A thin stdlib (`http.server`) layer over the server `submit` API —
`PagedInferenceServer` (the recommended backend: paged KV, radix prefix
reuse, chunked prefill, in-server speculative decoding) or the legacy
contiguous `InferenceServer`; both expose the same submit / num_active /
num_pending surface. Prompts go in as JSON, tokens stream back as
newline-delimited JSON the moment the scheduler emits them. No framework
dependency — the serving hot path stays the jitted TPU program; this
module only does sockets and JSON.

Protocol:
  POST /generate    {"prompt": "text"} or {"tokens": [1, 2, 3]},
                    optional "max_new_tokens". Response is
                    `application/x-ndjson`: one {"token": id,
                    "logprob": lp, "text": s}
                    line per generated token (text only when a tokenizer is
                    attached), then a final
                    {"done": true, "finish_reason": ...,
                    "tokens": [...], "logprobs": [...]} (logprobs aligned
                    with tokens).
  GET  /healthz     {"ok": true, "active": N, "pending": N}

Demo (server side: `python -m cloud_server_tpu.generate --serve-http 8000
...` or `HttpFrontend(srv, tok).start()`):

  curl -N -s localhost:8000/generate -d '{"prompt": "the meaning of"}'

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(network serving front-end).
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_STREAM_END = object()


class HttpFrontend:
    """Bind a serving backend (+ optional tokenizer) to an HTTP port.

    `srv` is a `PagedInferenceServer` or `InferenceServer` (any object
    with submit/num_active/num_pending). Its scheduler must be running
    (srv.start()) or be driven externally; this class never steps it.
    """

    def __init__(self, srv, tokenizer=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.srv = srv
        self.tokenizer = tokenizer
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet by default
                pass

            def _json(self, code: int, payload: dict) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/healthz":
                    self._json(404, {"error": "unknown path"})
                    return
                self._json(200, {"ok": True, "active": front.srv.num_active,
                                 "pending": front.srv.num_pending})

            def do_POST(self):
                if self.path != "/generate":
                    self._json(404, {"error": "unknown path"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("body must be a JSON object")
                    max_new = req.get("max_new_tokens")
                    if max_new is not None and not isinstance(max_new, int):
                        raise ValueError('"max_new_tokens" must be an int')
                    tokens = front._encode(req)
                except (ValueError, KeyError, TypeError) as exc:
                    self._json(400, {"error": str(exc)})
                    return

                q: queue.Queue = queue.Queue()
                try:
                    request = front.srv.submit(
                        tokens, max_new_tokens=max_new, stream=q.put)
                except ValueError as exc:
                    self._json(400, {"error": str(exc)})
                    return
                except RuntimeError as exc:  # scheduler stopped/crashed
                    self._json(503, {"error": str(exc)})
                    return

                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Connection", "close")
                self.end_headers()
                threading.Thread(  # unblock q.get when generation ends
                    target=lambda: (request._done.wait(),
                                    q.put(_STREAM_END)),
                    daemon=True).start()
                emitted = 0
                while True:
                    tok = q.get()
                    if tok is _STREAM_END:
                        break
                    line = {"token": int(tok)}
                    # _emit appends the logprob before invoking the stream
                    # callback, so it is present by the time we get here
                    if emitted < len(request.logprobs):
                        line["logprob"] = request.logprobs[emitted]
                    emitted += 1
                    if front.tokenizer is not None:
                        line["text"] = front.tokenizer.decode([int(tok)])
                    self.wfile.write((json.dumps(line) + "\n").encode())
                    self.wfile.flush()
                self.wfile.write((json.dumps(
                    {"done": True, "finish_reason": request.finish_reason,
                     "tokens": request.tokens,
                     "logprobs": request.logprobs}) + "\n").encode())

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    def _encode(self, req: dict) -> list[int]:
        if "tokens" in req:
            tokens = req["tokens"]
            if (not isinstance(tokens, list)
                    or not all(isinstance(t, int) for t in tokens)):
                raise ValueError('"tokens" must be a list of ints')
            return tokens
        if "prompt" in req:
            if self.tokenizer is None:
                raise ValueError(
                    'no tokenizer attached; send {"tokens": [...]} instead')
            return self.tokenizer.encode(req["prompt"]) or [0]
        raise ValueError('body needs "prompt" or "tokens"')

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="http-frontend")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

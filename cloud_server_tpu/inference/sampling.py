"""Token sampling — fully jittable (static shapes, no host sync).

top-k uses lax.top_k; top-p sorts once and masks the tail. Both reduce to
greedy when disabled. Temperature 0 is treated as greedy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import InferConfig

NEG_INF = -1e30


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    vals, _ = lax.top_k(logits, k)
    cutoff = vals[..., -1:]
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix with cumulative prob >= p; force the top
    # token in so p <= 0 degrades to greedy-ish rather than masking
    # everything (which would sample uniformly over the whole vocab).
    keep = (cum - probs < p).at[..., 0].set(True)
    cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _filtered_logits(logits: jnp.ndarray, cfg: InferConfig) -> jnp.ndarray:
    """Temperature / top-k / top-p filter chain. Single source of truth:
    `sample_logits` draws categorically from these, `sampling_probs`
    softmaxes them — keeping speculative decoding's output-distribution
    exactness structural rather than hand-synced. Callers handle
    temperature <= 0 (greedy) before calling."""
    x = logits / cfg.temperature
    if cfg.top_k > 0:
        x = _apply_top_k(x, cfg.top_k)
    if cfg.top_p < 1.0:
        x = _apply_top_p(x, cfg.top_p)
    return x


def sample_logits(logits: jnp.ndarray, rng: jax.Array,
                  cfg: InferConfig) -> jnp.ndarray:
    """logits: (B, V) f32 -> (B,) int32 sampled token ids."""
    if cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, _filtered_logits(logits, cfg), axis=-1).astype(jnp.int32)


def sampling_probs(logits: jnp.ndarray, cfg: InferConfig) -> jnp.ndarray:
    """The actual distribution `sample_logits` draws from: (..., V) f32
    probabilities after temperature / top-k / top-p (one-hot argmax for
    greedy). Speculative decoding's accept/residual rule needs these
    explicitly — acceptance must be measured against the FILTERED
    distribution or the output distribution would not match plain
    sampling."""
    if cfg.temperature <= 0.0:
        return jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1],
                              dtype=jnp.float32)
    return jax.nn.softmax(_filtered_logits(logits, cfg), axis=-1)


def sample_from_probs(probs: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Categorical draw from (..., V) probabilities -> (...,) int32."""
    return jax.random.categorical(
        rng, jnp.log(jnp.maximum(probs, 1e-30)), axis=-1).astype(jnp.int32)

"""Token sampling — fully jittable (static shapes, no host sync).

Two tiers share one filter chain (penalties -> temperature -> top-k ->
top-p -> min-p):

  * Server-global (`InferConfig` scalars, compiled in as statics):
    `sample_logits` / `sampling_probs`. The historical path — zero
    per-step overhead when every request uses the server defaults.
  * Per-request (`SamplingParams` -> `SamplingRows`, traced (B,) row
    arrays): `sample_logits_rows` / `sampling_probs_rows`. Each slot of
    the continuous batch carries its own temperature/top-k/top-p/min-p,
    repetition/presence/frequency penalties, and PRNG seed. The rows are
    tiny traced inputs, so mixing requests with different settings never
    recompiles; the servers only take this path when some live request
    actually needs it (static `use_rows` flag — the default-greedy hot
    loop pays nothing).

Per-request determinism: a seeded request's stream is reproducible
regardless of batch composition, because its draw at sequence position p
uses `fold_in(key(seed), p)` — no cross-slot RNG coupling. (With
in-server speculative decoding the OUTPUT DISTRIBUTION is preserved but
bitwise reproducibility is not: accept/residual draws are batch-wide.)

top-k uses a descending sort shared with top-p's cumulative mass scan;
both reduce to greedy when disabled. Temperature <= 0 is treated as
greedy (per row in the rows path). Penalties follow the OpenAI/vLLM
conventions: presence/frequency count GENERATED tokens only,
repetition_penalty (HF-style) spans prompt and generated tokens.
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from cloud_server_tpu.config import InferConfig

NEG_INF = -1e30
MAX_LOGIT_BIAS = 64  # static per-row logit_bias slots in SamplingRows
# padding token id for unused bias slots: far out of any vocab range, so
# mode="drop" scatters discard it (negative ids would wrap)
_BIAS_PAD = 2 ** 30


# ---------------------------------------------------------------------------
# Server-global path (InferConfig statics)
# ---------------------------------------------------------------------------


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    vals, _ = lax.top_k(logits, k)
    cutoff = vals[..., -1:]
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix with cumulative prob >= p; force the top
    # token in so p <= 0 degrades to greedy-ish rather than masking
    # everything (which would sample uniformly over the whole vocab).
    keep = (cum - probs < p).at[..., 0].set(True)
    cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _filtered_logits(logits: jnp.ndarray, cfg: InferConfig) -> jnp.ndarray:
    """Temperature / top-k / top-p filter chain. Single source of truth:
    `sample_logits` draws categorically from these, `sampling_probs`
    softmaxes them — keeping speculative decoding's output-distribution
    exactness structural rather than hand-synced. Callers handle
    temperature <= 0 (greedy) before calling."""
    x = logits / cfg.temperature
    if cfg.top_k > 0:
        x = _apply_top_k(x, cfg.top_k)
    if cfg.top_p < 1.0:
        x = _apply_top_p(x, cfg.top_p)
    return x


def sample_logits(logits: jnp.ndarray, rng: jax.Array,
                  cfg: InferConfig) -> jnp.ndarray:
    """logits: (B, V) f32 -> (B,) int32 sampled token ids."""
    if cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, _filtered_logits(logits, cfg), axis=-1).astype(jnp.int32)


def sampling_probs(logits: jnp.ndarray, cfg: InferConfig) -> jnp.ndarray:
    """The actual distribution `sample_logits` draws from: (..., V) f32
    probabilities after temperature / top-k / top-p (one-hot argmax for
    greedy). Speculative decoding's accept/residual rule needs these
    explicitly — acceptance must be measured against the FILTERED
    distribution or the output distribution would not match plain
    sampling."""
    if cfg.temperature <= 0.0:
        return jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1],
                              dtype=jnp.float32)
    return jax.nn.softmax(_filtered_logits(logits, cfg), axis=-1)


def sample_from_probs(probs: jnp.ndarray, rng: jax.Array) -> jnp.ndarray:
    """Categorical draw from (..., V) probabilities -> (...,) int32."""
    return jax.random.categorical(
        rng, jnp.log(jnp.maximum(probs, 1e-30)), axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Per-request path (SamplingParams -> SamplingRows)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls (every field optional; `None` and
    the neutral defaults inherit the server's `InferConfig`).

    `stop` holds TOKEN-ID sequences (text front-ends tokenize string
    stops before submit): generation ends with finish_reason "stop" the
    moment the output's tail equals one of them, and the matched tokens
    are removed
    from the result (OpenAI semantics). Tokens of a partially-matched
    stop sequence may already have been streamed by the time the match
    completes; the final token list is authoritative.

    `seed` makes the request's stream reproducible independent of batch
    composition (see module docstring for the speculative caveat).
    """

    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    min_p: float = 0.0
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    seed: int | None = None
    stop: tuple[tuple[int, ...], ...] = ()
    ignore_eos: bool = False
    # additive per-token logit adjustments ((token_id, bias) pairs, up
    # to MAX_LOGIT_BIAS) applied before the filter chain — OpenAI
    # logit_bias semantics
    logit_bias: tuple[tuple[int, float], ...] = ()
    # suppress EOS until this many tokens have been generated
    min_tokens: int = 0
    # regex the WHOLE generation must match (constrained decoding; see
    # inference/grammar.py for the supported syntax and the canned
    # json_object_regex helper). Paged server only; the server needs a
    # tokenizer to compile the pattern against.
    regex: str | None = None

    def __post_init__(self):
        if self.temperature is not None and self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k is not None and not -2 ** 31 < self.top_k < 2 ** 31:
            # rows are int32 device arrays; an unbounded value would
            # overflow in the scheduler thread and kill the server
            raise ValueError("top_k out of int32 range")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if not 0.0 <= self.min_p < 1.0:
            raise ValueError("min_p must be in [0, 1)")
        if self.repetition_penalty <= 0.0:
            raise ValueError("repetition_penalty must be > 0")
        if self.seed is not None and not 0 <= self.seed < 2 ** 32:
            # rows carry seeds as uint32; accepting wider values would
            # silently alias seeds differing only in high bits
            raise ValueError("seed must be in [0, 2**32)")
        # normalise stop to hashable tuples (callers may pass lists)
        stop = tuple(tuple(int(t) for t in s) for s in self.stop)
        if any(len(s) == 0 for s in stop):
            raise ValueError("empty stop sequence")
        object.__setattr__(self, "stop", stop)
        bias = tuple((int(t), float(b)) for t, b in self.logit_bias)
        if len(bias) > MAX_LOGIT_BIAS:
            raise ValueError(
                f"at most {MAX_LOGIT_BIAS} logit_bias entries")
        if any(t < 0 or not math.isfinite(b) for t, b in bias):
            raise ValueError("logit_bias needs token ids >= 0 and finite "
                             "biases")
        object.__setattr__(self, "logit_bias", bias)
        if not 0 <= self.min_tokens < 2 ** 31:
            raise ValueError("min_tokens must be a small non-negative int")
        if self.regex is not None and (self.min_tokens > 0
                                       or self.ignore_eos):
            # either would force generation past an accept-only DFA
            # state where ONLY EOS is allowed, leaving no legal token
            raise ValueError(
                "regex cannot be combined with min_tokens or ignore_eos "
                "(the grammar decides when generation may end)")

    def needs_device_rows(self, cfg: InferConfig) -> bool:
        """True when this request's DEVICE-side sampling differs from the
        server defaults (stop/ignore_eos are host-side and free)."""
        return ((self.temperature is not None
                 and self.temperature != cfg.temperature)
                or (self.top_k is not None and self.top_k != cfg.top_k)
                or (self.top_p is not None and self.top_p != cfg.top_p)
                or self.min_p > 0.0
                or self.needs_penalty_state()
                or self.seed is not None
                or bool(self.logit_bias)
                or self.min_tokens > 0
                or self.regex is not None)

    def needs_penalty_state(self) -> bool:
        """True when sampling this request reads the (B, V) prompt-mask /
        output-count buffers — the servers materialize those lazily on
        the first such request, so penalty-free deployments never pay
        their HBM or scatter cost."""
        return (self.repetition_penalty != 1.0
                or self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0)

    def resolve(self, cfg: InferConfig, default_seed: int) -> tuple:
        """Concrete (temperature, top_k, top_p, min_p, rep, pres, freq,
        seed, bias_ids, bias_vals, min_new) row values with `None`
        fields inherited from `cfg` and logit_bias padded to
        MAX_LOGIT_BIAS slots."""
        ids = [t for t, _ in self.logit_bias]
        vals = [b for _, b in self.logit_bias]
        pad = MAX_LOGIT_BIAS - len(ids)
        return (
            cfg.temperature if self.temperature is None else self.temperature,
            cfg.top_k if self.top_k is None else self.top_k,
            cfg.top_p if self.top_p is None else self.top_p,
            self.min_p, self.repetition_penalty, self.presence_penalty,
            self.frequency_penalty,
            default_seed if self.seed is None else self.seed,
            ids + [_BIAS_PAD] * pad, vals + [0.0] * pad, self.min_tokens)


class SamplingRows(NamedTuple):
    """Per-slot sampling parameters as device rows (a pytree of (B,) or
    (B, K) arrays — traced jit inputs, never statics)."""

    temperature: jnp.ndarray  # (B,) f32; <= 0 means greedy for that row
    top_k: jnp.ndarray        # (B,) i32; <= 0 disables
    top_p: jnp.ndarray        # (B,) f32
    min_p: jnp.ndarray        # (B,) f32
    rep: jnp.ndarray          # (B,) f32 repetition penalty (1 = off)
    pres: jnp.ndarray         # (B,) f32 presence penalty
    freq: jnp.ndarray         # (B,) f32 frequency penalty
    seed: jnp.ndarray         # (B,) uint32 per-request PRNG seed
    bias_ids: jnp.ndarray     # (B, MAX_LOGIT_BIAS) i32, _BIAS_PAD unused
    bias_vals: jnp.ndarray    # (B, MAX_LOGIT_BIAS) f32
    min_new: jnp.ndarray      # (B,) i32 min generated tokens before EOS
    plen: jnp.ndarray         # (B,) i32 original prompt length (set by
    #                           the server at admission — generated-count
    #                           accounting for min_new)


def make_rows(params_list: Sequence[SamplingParams | None],
              cfg: InferConfig, default_seeds: Sequence[int],
              prompt_lens: Sequence[int] | None = None) -> SamplingRows:
    """Host-side builder: one numpy row per request (jnp.asarray at the
    dispatch boundary). `prompt_lens` are the ORIGINAL prompt lengths
    (min_tokens accounting); zeros when omitted."""
    vals = [(p or SamplingParams()).resolve(cfg, int(s))
            for p, s in zip(params_list, default_seeds)]
    t, k, p, mp, rep, pres, freq, seed, bids, bvals, mn = zip(*vals)
    if prompt_lens is None:
        prompt_lens = [0] * len(vals)
    return SamplingRows(
        temperature=np.asarray(t, np.float32),
        top_k=np.asarray(k, np.int32),
        top_p=np.asarray(p, np.float32),
        min_p=np.asarray(mp, np.float32),
        rep=np.asarray(rep, np.float32),
        pres=np.asarray(pres, np.float32),
        freq=np.asarray(freq, np.float32),
        seed=np.asarray(np.asarray(seed, np.int64) & 0xFFFFFFFF, np.uint32),
        bias_ids=np.asarray(bids, np.int32),
        bias_vals=np.asarray(bvals, np.float32),
        min_new=np.asarray(mn, np.int32),
        plen=np.asarray(prompt_lens, np.int32))


def zero_rows(n: int) -> SamplingRows:
    """All-zero rows (temperature 0 = greedy) — initial state for slots
    nothing has been admitted into."""
    return SamplingRows(
        temperature=jnp.zeros((n,), jnp.float32),
        top_k=jnp.zeros((n,), jnp.int32),
        top_p=jnp.ones((n,), jnp.float32),
        min_p=jnp.zeros((n,), jnp.float32),
        rep=jnp.ones((n,), jnp.float32),
        pres=jnp.zeros((n,), jnp.float32),
        freq=jnp.zeros((n,), jnp.float32),
        seed=jnp.zeros((n,), jnp.uint32),
        bias_ids=jnp.full((n, MAX_LOGIT_BIAS), _BIAS_PAD, jnp.int32),
        bias_vals=jnp.zeros((n, MAX_LOGIT_BIAS), jnp.float32),
        min_new=jnp.zeros((n,), jnp.int32),
        plen=jnp.zeros((n,), jnp.int32))


def set_rows(state: SamplingRows, slots: jnp.ndarray,
             rows: SamplingRows) -> SamplingRows:
    """Scatter admission rows into per-slot row state (out-of-range slot
    indices drop — the padding convention of the admission dispatches)."""
    return SamplingRows(*[
        s.at[slots].set(r.astype(s.dtype), mode="drop")
        for s, r in zip(state, rows)])


def _expand(row: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """(B,) -> (B, 1, ..., 1) matching ref's rank for broadcasting."""
    return row.reshape(row.shape[0], *([1] * (ref.ndim - 1)))


def penalised_logits(logits: jnp.ndarray, rows: SamplingRows,
                     prompt_mask: jnp.ndarray,
                     out_counts: jnp.ndarray) -> jnp.ndarray:
    """Presence/frequency penalties over generated-token counts
    (`out_counts`, broadcastable to `logits`) and HF-style repetition
    penalty over prompt-or-generated (`prompt_mask` (B, V) bool).

    Order matches vLLM's apply_penalties: the repetition penalty
    divides/multiplies the RAW logits, then presence/frequency subtract
    — so a pres/freq sign flip can never invert the repetition
    penalty's direction."""
    x = logits.astype(jnp.float32)
    counts = out_counts.astype(jnp.float32)
    pm = prompt_mask if prompt_mask.ndim == x.ndim else prompt_mask[:, None]
    seen = pm | (out_counts > 0)
    rep = _expand(rows.rep, x)
    x = jnp.where(seen, jnp.where(x > 0, x / rep, x * rep), x)
    return (x - _expand(rows.pres, x) * (counts > 0)
            - _expand(rows.freq, x) * counts)


def filtered_logits_rows(logits: jnp.ndarray, rows: SamplingRows, *,
                         prompt_mask: jnp.ndarray | None = None,
                         out_counts: jnp.ndarray | None = None,
                         positions: jnp.ndarray | None = None,
                         eos_id: int = -1, use_bias: bool = True,
                         allowed_mask: jnp.ndarray | None = None):
    """Per-row filter chain over (B, ..., V) logits: grammar mask ->
    logit_bias ->
    penalties -> min_tokens EOS suppression -> temperature -> top-k ->
    top-p -> min-p. `positions` (logits.shape[:-1]) are the absolute
    sequence positions being sampled — with `eos_id`, they drive the
    min_tokens suppression (generated-so-far = position - plen).
    `use_bias` is the servers' static no-bias-in-batch gate (the (B, V)
    bias table shouldn't tax rows-mode batches that never asked for it).

    Returns (filtered logits for categorical draws, post-penalty
    pre-temperature logits — the greedy-row argmax source)."""
    x = logits.astype(jnp.float32)
    b = x.shape[0]
    if allowed_mask is not None:
        # constrained decoding: tokens outside the grammar's allowed set
        # are impossible — applied FIRST so greedy, penalties, and
        # top-k/p all operate on the constrained distribution
        x = jnp.where(allowed_mask, x, NEG_INF)
    if use_bias:
        # logit_bias: build a per-row (B, V) additive table once
        # (padding slots point far out of the vocab and drop),
        # broadcast over any window dimension
        bias = jnp.zeros((b, x.shape[-1]), jnp.float32).at[
            jnp.arange(b)[:, None], rows.bias_ids].add(rows.bias_vals,
                                                       mode="drop")
        x = x + bias.reshape(bias.shape[:1] + (1,) * (x.ndim - 2)
                             + bias.shape[1:])
    if prompt_mask is not None:
        x = penalised_logits(x, rows, prompt_mask, out_counts)
    if positions is not None and eos_id >= 0:
        # min_tokens: the token at absolute position p is generated
        # index p - plen; suppress EOS while that is < min_new
        gen = positions - rows.plen.reshape(
            (b,) + (1,) * (positions.ndim - 1))
        suppress = (gen < rows.min_new.reshape(
            (b,) + (1,) * (positions.ndim - 1)))[..., None]
        x = jnp.where(suppress & (jnp.arange(x.shape[-1]) == eos_id),
                      NEG_INF, x)
    raw = x
    xt = x / jnp.maximum(_expand(rows.temperature, x), 1e-6)
    v = x.shape[-1]
    k = _expand(jnp.where(rows.top_k <= 0, v, rows.top_k), x)
    xs = jnp.sort(xt, axis=-1)[..., ::-1]
    ps = jax.nn.softmax(xs, axis=-1)
    cum = jnp.cumsum(ps, axis=-1)
    rank = jnp.arange(v)
    keep = (rank < k) & ((cum - ps) < _expand(rows.top_p, x))
    keep = keep.at[..., 0].set(True)  # never mask everything
    cutoff = jnp.min(jnp.where(keep, xs, jnp.inf), axis=-1, keepdims=True)
    mask = xt >= cutoff
    # min-p: relative to the max probability of the temperature-scaled
    # distribution; the argmax always survives (p_max >= min_p * p_max)
    probs = jax.nn.softmax(xt, axis=-1)
    mask &= probs >= _expand(rows.min_p, x) * jnp.max(ps, axis=-1,
                                                      keepdims=True)
    return jnp.where(mask, xt, NEG_INF), raw


def _row_keys(rows: SamplingRows, positions: jnp.ndarray) -> jax.Array:
    """One key per row: fold the absolute sequence position into the
    request's seed key — draws depend only on (seed, position), never on
    which other requests share the batch."""
    def mk(seed, pos):
        return jax.random.fold_in(jax.random.key(seed), pos)

    return jax.vmap(mk)(rows.seed, positions)


def sample_logits_rows(logits: jnp.ndarray, rows: SamplingRows,
                       positions: jnp.ndarray, *,
                       prompt_mask: jnp.ndarray | None = None,
                       out_counts: jnp.ndarray | None = None,
                       eos_id: int = -1,
                       use_bias: bool = True,
                       allowed_mask: jnp.ndarray | None = None
                       ) -> jnp.ndarray:
    """Per-row draw: (B, V) logits -> (B,) int32. `positions` (B,) is the
    absolute sequence position being sampled (the fold_in counter and
    the min_tokens generated-count reference)."""
    filt, raw = filtered_logits_rows(logits, rows, prompt_mask=prompt_mask,
                                     out_counts=out_counts,
                                     positions=positions, eos_id=eos_id,
                                     use_bias=use_bias,
                                     allowed_mask=allowed_mask)
    keys = _row_keys(rows, positions)
    sampled = jax.vmap(jax.random.categorical)(keys, filt)
    greedy = jnp.argmax(raw, axis=-1)
    return jnp.where(rows.temperature <= 0.0, greedy,
                     sampled).astype(jnp.int32)


def sampling_probs_rows(logits: jnp.ndarray, rows: SamplingRows, *,
                        prompt_mask: jnp.ndarray | None = None,
                        out_counts: jnp.ndarray | None = None,
                        positions: jnp.ndarray | None = None,
                        eos_id: int = -1,
                        use_bias: bool = True,
                        allowed_mask: jnp.ndarray | None = None
                        ) -> jnp.ndarray:
    """Rows analogue of `sampling_probs`: the exact per-row distribution
    `sample_logits_rows` draws from, over (B, ..., V) logits (speculative
    verification scores whole windows — pass cumulative `out_counts` and
    per-position `positions` matching the window so penalties and
    min_tokens stay exact position by position)."""
    filt, raw = filtered_logits_rows(logits, rows, prompt_mask=prompt_mask,
                                     out_counts=out_counts,
                                     positions=positions, eos_id=eos_id,
                                     use_bias=use_bias,
                                     allowed_mask=allowed_mask)
    probs = jax.nn.softmax(filt, axis=-1)
    onehot = jax.nn.one_hot(jnp.argmax(raw, axis=-1), logits.shape[-1],
                            dtype=probs.dtype)
    return jnp.where(_expand(rows.temperature <= 0.0, probs), onehot, probs)

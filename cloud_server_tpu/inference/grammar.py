"""Regex-constrained decoding: host-side compilation to a token-level
DFA, consumed device-side with zero per-token host sync.

The TPU-first structured-output design (cf. the Outlines/vLLM FSM
approach, re-built for XLA):

  1. A regex over BYTES compiles to a DFA (Thompson NFA -> subset
     construction, byte-class alphabet compression).
  2. The DFA lifts to TOKEN granularity against the serving tokenizer:
     `next_state[s, t]` = the DFA state after consuming token t's UTF-8
     bytes from state s (DEAD when any byte dies). One (S, V) int32
     table + an (S,) accept vector per pattern, built once and cached.
  3. The server keeps a REGISTRY of active patterns stacked into one
     (G, S_max, V) device table. Each constrained slot carries a
     grammar id and a current DFA state; every decode dispatch gathers
     its (B, V) allowed mask from the stack, masks the logits ahead of
     the sampling filter chain, and advances the states with the
     sampled tokens — all inside the jitted program. EOS is allowed
     exactly in accepting states, so generation can only end on a
     complete match.

Supported syntax: literals, `.`, escapes (\\d \\w \\s \\n \\t \\r and
escaped metachars), character classes `[a-z0-9_]` (ranges, negation),
grouping `(...)`, alternation `|`, quantifiers `* + ?` and bounded
`{m}` / `{m,}` / `{m,n}`. Patterns are anchored (the whole generation
must match). Multi-byte UTF-8 literals work byte-by-byte; `.` matches
any single byte except newline (byte semantics — document for users).

Token byte mapping: exact for the framework's ByteTokenizer; for HF
fast tokenizers the per-token string is recovered via `id_to_token`
with the GPT-2 byte-level alphabet / sentencepiece markers decoded.
Ids the tokenizer cannot spell (specials, out-of-tokenizer padding of
the model vocab) are never allowed inside a constrained generation.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(structured / constrained generation).
"""

from __future__ import annotations

import functools
import re as _pyre
from typing import Sequence

import numpy as np

MAX_DFA_STATES = 2048  # compilation fails loudly past this; the token
#                        table is (S, V) int32, so device memory is
#                        S * vocab * 4 bytes — see compile_token_dfa's
#                        byte guard
MAX_TABLE_BYTES = 256 << 20  # refuse token tables past 256 MB
DEAD = -1


# ---------------------------------------------------------------------------
# regex parsing -> NFA (Thompson construction over byte sets)
# ---------------------------------------------------------------------------


class _Frag:
    """NFA fragment: start state + list of dangling (state, key) arrows
    to patch. NFA: dict state -> list of (byteset | None, target);
    None = epsilon."""

    __slots__ = ("start", "outs")

    def __init__(self, start, outs):
        self.start = start
        self.outs = outs


_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = frozenset(list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
                  + list(range(0x61, 0x7B)) + [0x5F])
_SPACE = frozenset(b" \t\n\r\x0b\x0c")
_ANY = frozenset(set(range(256)) - {0x0A})  # '.' (no newline)


class _Parser:
    """Recursive-descent regex -> NFA."""

    def __init__(self, pattern: str):
        self.src = pattern.encode("utf-8")
        self.i = 0
        self.nfa: list[list] = []  # state -> [(byteset|None, target)]

    def _new_state(self) -> int:
        self.nfa.append([])
        return len(self.nfa) - 1

    def _peek(self):
        return self.src[self.i] if self.i < len(self.src) else None

    def _eat(self):
        b = self.src[self.i]
        self.i += 1
        return b

    # grammar: alt := concat ('|' concat)* ; concat := repeat* ;
    # repeat := atom ('*'|'+'|'?'|'{m,n}')* ; atom := literal | class |
    # '(' alt ')' | '.' | escape
    def parse(self) -> _Frag:
        frag = self._alt()
        if self.i != len(self.src):
            raise ValueError(
                f"regex: unexpected {chr(self._peek())!r} at byte {self.i}")
        return frag

    def _alt(self) -> _Frag:
        frags = [self._concat()]
        while self._peek() == 0x7C:  # '|'
            self._eat()
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        start = self._new_state()
        outs = []
        for f in frags:
            self.nfa[start].append((None, f.start))
            outs.extend(f.outs)
        return _Frag(start, outs)

    def _concat(self) -> _Frag:
        frags = []
        while self._peek() is not None and self._peek() not in (0x7C, 0x29):
            frags.append(self._repeat())
        if not frags:
            s = self._new_state()
            return _Frag(s, [(s, None)])  # empty: dangling epsilon-ish
        cur = frags[0]
        for nxt in frags[1:]:
            self._patch(cur.outs, nxt.start)
            cur = _Frag(cur.start, nxt.outs)
        return cur

    def _patch(self, outs, target: int) -> None:
        for state, key in outs:
            self.nfa[state].append((key, target))

    def _repeat(self) -> _Frag:
        frag = self._atom()
        while True:
            c = self._peek()
            if c == 0x2A:  # '*'
                self._eat()
                frag = self._star(frag)
            elif c == 0x2B:  # '+'
                self._eat()
                frag = self._plus(frag)
            elif c == 0x3F:  # '?'
                self._eat()
                frag = self._opt(frag)
            elif c == 0x7B:  # '{'
                frag = self._bounded(frag)
            else:
                return frag

    def _clone(self, frag: _Frag) -> _Frag:
        """Deep-copy a fragment's reachable subgraph (bounded repeats
        expand to copies)."""
        mapping = {}

        def copy(s):
            if s in mapping:
                return mapping[s]
            ns = self._new_state()
            mapping[s] = ns
            for key, tgt in list(self.nfa[s]):
                self.nfa[ns].append((key, copy(tgt)))
            return ns

        start = copy(frag.start)
        outs = [(mapping[s], key) for s, key in frag.outs]
        return _Frag(start, outs)

    def _star(self, frag: _Frag) -> _Frag:
        hub = self._new_state()
        self.nfa[hub].append((None, frag.start))
        self._patch(frag.outs, hub)
        return _Frag(hub, [(hub, None)])

    def _plus(self, frag: _Frag) -> _Frag:
        hub = self._new_state()
        self._patch(frag.outs, hub)
        self.nfa[hub].append((None, frag.start))
        return _Frag(frag.start, [(hub, None)])

    def _opt(self, frag: _Frag) -> _Frag:
        hub = self._new_state()
        self.nfa[hub].append((None, frag.start))
        return _Frag(hub, frag.outs + [(hub, None)])

    def _bounded(self, frag: _Frag) -> _Frag:
        assert self._eat() == 0x7B
        spec = bytearray()
        while self._peek() is not None and self._peek() != 0x7D:
            spec.append(self._eat())
        if self._peek() is None:
            raise ValueError("regex: unterminated {m,n}")
        self._eat()  # '}'
        parts = spec.decode().split(",")
        try:
            m = int(parts[0])
            n = (m if len(parts) == 1
                 else (None if parts[1] == "" else int(parts[1])))
        except ValueError as exc:
            raise ValueError(f"regex: bad repeat {{{spec.decode()}}}") \
                from exc
        if n is not None and (m > n or m < 0):
            raise ValueError(f"regex: bad repeat bounds {{{m},{n}}}")
        if m > 256 or (n or 0) > 256:
            raise ValueError("regex: repeat bound > 256")

        def chain_onto(cur: _Frag | None, piece: _Frag) -> _Frag:
            if cur is None:
                return piece
            self._patch(cur.outs, piece.start)
            return _Frag(cur.start, piece.outs)

        # m required copies, then (n - m) optional copies (each
        # skippable — `_opt` keeps the skip arrow in its outs) or a
        # star tail when n is None. ALL clones are made up front, while
        # `frag` is still pristine — cloning after a patch would copy
        # the patched-in arrows and graft spurious subgraphs into later
        # copies.
        total = m + 1 if n is None else n
        copies = [self._clone(frag) for _ in range(max(total - 1, 0))]
        copies.append(frag)  # the original is always the LAST piece
        chain: _Frag | None = None
        for _ in range(m):
            chain = chain_onto(chain, copies.pop(0))
        if n is None:
            tail = self._star(copies.pop(0))
            return chain_onto(chain, tail)
        for _ in range(n - m):
            chain = chain_onto(chain, self._opt(copies.pop(0)))
        if chain is None:  # {0,0}: matches only the empty string
            s = self._new_state()
            return _Frag(s, [(s, None)])
        return chain

    def _atom(self) -> _Frag:
        c = self._peek()
        if c is None:
            raise ValueError("regex: unexpected end")
        if c == 0x28:  # '('
            self._eat()
            # non-capturing group marker (?: is accepted and ignored
            if (self._peek() == 0x3F and self.i + 1 < len(self.src)
                    and self.src[self.i + 1] == 0x3A):
                self._eat()
                self._eat()
            frag = self._alt()
            if self._peek() != 0x29:
                raise ValueError("regex: missing )")
            self._eat()
            return frag
        if c == 0x5B:  # '['
            return self._charclass()
        if c == 0x2E:  # '.'
            self._eat()
            return self._byteset(_ANY)
        if c == 0x5C:  # '\'
            self._eat()
            return self._byteset(self._escape())
        if c in (0x2A, 0x2B, 0x3F, 0x7B, 0x7D, 0x29, 0x7C):
            raise ValueError(f"regex: stray {chr(c)!r}")
        # literal byte (multi-byte UTF-8 chars arrive byte by byte)
        return self._byteset(frozenset([self._eat()]))

    def _escape(self) -> frozenset:
        if self._peek() is None:
            raise ValueError("regex: trailing backslash")
        e = self._eat()
        table = {0x64: _DIGIT, 0x77: _WORD, 0x73: _SPACE,  # d w s
                 0x6E: frozenset([0x0A]), 0x74: frozenset([0x09]),
                 0x72: frozenset([0x0D])}  # n t r
        if e in table:
            return table[e]
        if e == 0x78:  # \xNN
            if self.i + 2 > len(self.src):
                raise ValueError("regex: truncated \\xNN escape")
            try:
                val = int(self.src[self.i:self.i + 2].decode(), 16)
            except ValueError as exc:
                raise ValueError("regex: bad \\xNN escape") from exc
            self.i += 2
            return frozenset([val])
        if e == 0x44:  # \D
            return frozenset(set(range(256)) - _DIGIT)
        if e == 0x57:  # \W
            return frozenset(set(range(256)) - _WORD)
        if e == 0x53:  # \S
            return frozenset(set(range(256)) - _SPACE)
        return frozenset([e])  # escaped literal (\. \\ \[ ...)

    def _byteset(self, bs: frozenset) -> _Frag:
        s = self._new_state()
        return _Frag(s, [(s, bs)])

    def _charclass(self) -> _Frag:
        assert self._eat() == 0x5B
        negate = False
        if self._peek() == 0x5E:  # '^'
            negate = True
            self._eat()
        members: set[int] = set()
        first = True

        def read_one() -> frozenset:
            if self._peek() == 0x5C:
                self._eat()
                return self._escape()
            return frozenset([self._eat()])

        while True:
            c = self._peek()
            if c is None:
                raise ValueError("regex: unterminated [...]")
            if c == 0x5D and not first:  # ']'
                self._eat()
                break
            first = False
            item = read_one()
            if (self._peek() == 0x2D and self.i + 1 < len(self.src)
                    and self.src[self.i + 1] != 0x5D):
                self._eat()  # '-'
                hi_set = read_one()
                if len(item) != 1 or len(hi_set) != 1:
                    raise ValueError(
                        "regex: range endpoints in [...] must be single "
                        "bytes")
                lo, hi = next(iter(item)), next(iter(hi_set))
                if hi < lo:
                    raise ValueError("regex: reversed range in [...]")
                members |= set(range(lo, hi + 1))
            else:
                members |= item
        if negate:
            members = set(range(256)) - members
        if not members:
            raise ValueError("regex: empty character class")
        return self._byteset(frozenset(members))


# ---------------------------------------------------------------------------
# NFA -> byte DFA (subset construction)
# ---------------------------------------------------------------------------


class ByteDFA:
    """trans: (S, 256) int32 (DEAD = dead); accept: (S,) bool; start 0."""

    def __init__(self, trans: np.ndarray, accept: np.ndarray):
        self.trans = trans
        self.accept = accept

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    def run(self, state: int, data: bytes) -> int:
        for b in data:
            if state == DEAD:
                return DEAD
            state = int(self.trans[state, b])
        return state

    def matches(self, data: bytes) -> bool:
        s = self.run(0, data)
        return s != DEAD and bool(self.accept[s])


def compile_byte_dfa(pattern: str) -> ByteDFA:
    parser = _Parser(pattern)
    frag = parser.parse()
    nfa = parser.nfa
    final = len(nfa)
    nfa.append([])  # the single accepting NFA state
    parser._patch(frag.outs, final)

    def eps_closure(states: frozenset) -> frozenset:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for key, tgt in nfa[s]:
                if key is None and tgt not in seen:
                    seen.add(tgt)
                    stack.append(tgt)
        return frozenset(seen)

    start = eps_closure(frozenset([frag.start]))
    dfa_ids = {start: 0}
    order = [start]
    trans_rows = []
    qi = 0
    while qi < len(order):
        cur = order[qi]
        qi += 1
        row = np.full((256,), DEAD, np.int32)
        # group target NFA-state-sets by byte
        per_byte: dict[int, set] = {}
        for s in cur:
            for key, tgt in nfa[s]:
                if key is None:
                    continue
                for b in key:
                    per_byte.setdefault(b, set()).add(tgt)
        for b, tgts in per_byte.items():
            nxt = eps_closure(frozenset(tgts))
            if nxt not in dfa_ids:
                if len(dfa_ids) >= MAX_DFA_STATES:
                    raise ValueError(
                        f"regex compiles to more than {MAX_DFA_STATES} "
                        "DFA states; simplify the pattern")
                dfa_ids[nxt] = len(dfa_ids)
                order.append(nxt)
            row[b] = dfa_ids[nxt]
        trans_rows.append(row)
    trans = np.stack(trans_rows)
    accept = np.asarray([final in st for st in order])
    return _trim_coaccessible(ByteDFA(trans, accept))


def _trim_coaccessible(dfa: ByteDFA) -> ByteDFA:
    """Remove states from which no accepting state is reachable.

    Constrained decoding fundamentally requires `allowed => the match
    can still complete`: a transition into a dead-end state would let
    generation wander somewhere nothing (not even EOS) is ever allowed
    again. Matching semantics are unchanged — dead-end paths never
    accepted anyway.
    """
    n = dfa.num_states
    safe = np.where(dfa.trans == DEAD, n, dfa.trans)  # n = sink row
    reach = np.concatenate([dfa.accept, [False]])  # sink never reaches
    while True:
        new = reach.copy()
        new[:n] |= reach[safe].any(axis=1)
        if (new == reach).all():
            break
        reach = new
    if not reach[0]:
        raise ValueError("regex matches nothing (empty language)")
    keep = reach[:n]
    remap = np.full((n + 1,), DEAD, np.int64)
    remap[:n][keep] = np.arange(int(keep.sum()))
    trans = remap[safe[keep]].astype(np.int32)
    return ByteDFA(trans, dfa.accept[keep])


# ---------------------------------------------------------------------------
# token byte mapping + token-level lift
# ---------------------------------------------------------------------------

# GPT-2 byte-level BPE alphabet: printable stand-ins for raw bytes
@functools.lru_cache(maxsize=1)
def _gpt2_unicode_to_byte() -> dict[str, int]:
    bs = (list(range(0x21, 0x7F)) + list(range(0xA1, 0xAD))
          + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def _declared_special_ids(tokenizer, inner) -> set[int] | None:
    """Special-token ids from the tokenizer's OWN declaration: the
    added-token registry's `special` flags plus the wrapper's resolved
    bos/eos/pad. Returns None when the tokenizer declares nothing, in
    which case the caller falls back to a string-shape heuristic — a
    heuristic alone would misclassify real BPE merges like '[]', '[0]'
    or '<div>' and make them unreachable under a grammar."""
    getter = getattr(inner, "get_added_tokens_decoder", None)
    if getter is None:
        asi = getattr(tokenizer, "all_special_ids", None)
        return {int(i) for i in asi} if asi else None
    try:
        ids = {int(tid) for tid, tok in getter().items()
               if getattr(tok, "special", True)}
    except Exception:  # pragma: no cover — tokenizers API drift
        return None
    # base-vocab specials the wrapper resolved at construction (some
    # tokenizer.json files bake bos/eos into the vocab, not added)
    for name in ("bos_id", "eos_id"):
        v = getattr(tokenizer, name, None)
        if isinstance(v, int) and v >= 0:
            ids.add(v)
    # pad only when the tokenizer actually DECLARED one — the wrapper's
    # fallback (eos, else 0) would otherwise ban real vocab id 0
    pad = getattr(tokenizer, "pad_id", None)
    if (getattr(tokenizer, "pad_is_declared", True)
            and isinstance(pad, int) and pad >= 0):
        ids.add(pad)
    return ids


_BYTE_FALLBACK = _pyre.compile(r"^<0x([0-9A-Fa-f]{2})>$")


def _has_byte_fallback(inner) -> bool:
    """True when the vocab carries the sentencepiece byte-fallback
    convention: ALL 256 '<0xHH>' tokens present. A partial set (e.g. a
    BPE merge that happens to spell '<0x0A>') stays literal text."""
    t2i = getattr(inner, "token_to_id", None)
    if t2i is None:
        return False
    return all(t2i(f"<0x{b:02X}>") is not None for b in range(256))


def token_bytes(tokenizer, vocab_size: int) -> list[bytes | None]:
    """Per-token UTF-8 byte strings; None = unspellable (specials, ids
    past the tokenizer). Exact for ByteTokenizer; HF fast tokenizers go
    through id_to_token with byte-level/sentencepiece markers decoded."""
    out: list[bytes | None] = [None] * vocab_size
    inner = getattr(tokenizer, "_tok", None)
    if inner is not None and hasattr(inner, "id_to_token"):
        g2b = _gpt2_unicode_to_byte()
        specials = _declared_special_ids(tokenizer, inner)
        byte_fb = _has_byte_fallback(inner)
        for i in range(min(vocab_size, tokenizer.vocab_size)):
            s = inner.id_to_token(i)
            if s is None:
                continue
            if specials is not None:
                if i in specials:
                    continue  # never valid inside a constraint
            elif (s.startswith("<") and s.endswith(">")) or (
                    s.startswith("[") and s.endswith("]")):
                continue  # undeclared tokenizer: shape heuristic
            if byte_fb:
                m = _BYTE_FALLBACK.match(s)
                if m:  # sentencepiece byte fallback: '<0x0A>' IS \n
                    out[i] = bytes([int(m.group(1), 16)])
                    continue
            if all(ch in g2b for ch in s):  # byte-level BPE alphabet
                out[i] = bytes(g2b[ch] for ch in s)
            else:  # sentencepiece-style: ▁ marks a leading space
                out[i] = s.replace("▁", " ").encode("utf-8")
        return out
    # byte tokenizer (ids 0..255 are raw bytes; specials unspellable)
    for i in range(min(256, vocab_size)):
        out[i] = bytes([i])
    return out


class TokenDFA:
    """Token-level grammar table.

    next_state: (S, V) int32, DEAD where the token is not allowed;
    accept: (S,) bool — EOS is allowed exactly in accepting states.
    """

    def __init__(self, next_state: np.ndarray, accept: np.ndarray,
                 pattern: str):
        self.next_state = next_state
        self.accept = accept
        self.pattern = pattern

    @property
    def num_states(self) -> int:
        return self.next_state.shape[0]

    def walk(self, tokens: Sequence[int], state: int = 0) -> int:
        """Host-side replay (continuations after preemption)."""
        for t in tokens:
            if state == DEAD:
                return DEAD
            state = int(self.next_state[state, t])
        return state


def compile_token_dfa(pattern: str, tok_bytes: Sequence[bytes | None]
                      ) -> TokenDFA:
    """Lift the pattern's byte DFA to token granularity.

    Vectorised over the vocab: token transitions advance byte-by-byte
    through (S, 256) gathers — O(max_token_len) numpy passes, not
    O(S * V) python loops.
    """
    dfa = compile_byte_dfa(pattern)
    s_count = dfa.num_states
    v = len(tok_bytes)
    if s_count * v * 4 > MAX_TABLE_BYTES:
        raise ValueError(
            f"pattern needs {s_count} DFA states x {v} vocab = "
            f"{s_count * v * 4 >> 20} MB of token table (> "
            f"{MAX_TABLE_BYTES >> 20} MB); simplify the pattern or use a "
            "smaller-vocab tokenizer")
    max_len = max((len(b) for b in tok_bytes if b), default=1)
    # states (S, V): start every column at its row state; dead columns
    # (unspellable tokens) start DEAD
    states = np.tile(np.arange(s_count, dtype=np.int32)[:, None], (1, v))
    spell = np.asarray([b is not None for b in tok_bytes])
    states[:, ~spell] = DEAD
    lens = np.asarray([len(b) if b else 0 for b in tok_bytes])
    byte_mat = np.zeros((max_len, v), np.int32)
    for i, b in enumerate(tok_bytes):
        if b:
            byte_mat[:len(b), i] = np.frombuffer(b, np.uint8)
    trans = np.concatenate(  # DEAD row sends everything to DEAD
        [dfa.trans, np.full((1, 256), DEAD, np.int32)], axis=0)
    for step in range(max_len):
        live = lens > step
        nxt = trans[states[:, live], byte_mat[step, live]]
        states[:, live] = nxt
    # zero-length tokens (shouldn't exist) end where they started; fine
    return TokenDFA(states, dfa.accept.copy(), pattern)


class GrammarCache:
    """Per-(tokenizer, vocab) compile cache: pattern -> TokenDFA."""

    def __init__(self, tokenizer, vocab_size: int):
        self._tok_bytes = token_bytes(tokenizer, vocab_size)
        self._cache: dict[str, TokenDFA] = {}

    def get(self, pattern: str) -> TokenDFA:
        hit = self._cache.get(pattern)
        if hit is None:
            hit = compile_token_dfa(pattern, self._tok_bytes)
            self._cache[pattern] = hit
        return hit


# ---------------------------------------------------------------------------
# canned patterns
# ---------------------------------------------------------------------------

_JSON_STRING = r'"([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})*"'
_JSON_NUMBER = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+\-]?[0-9]+)?"
_JSON_SCALAR = f"({_JSON_STRING}|{_JSON_NUMBER}|true|false|null)"


def json_object_regex(max_depth: int = 1) -> str:
    """A bounded-depth JSON object/array grammar as a regex (regular
    languages cannot nest unboundedly; depth-k JSON is regular). Depth 1
    = flat objects/arrays of scalars (~310 DFA states); each extra level
    multiplies states ~4x (depth 2 ~1.3k, depth 3 ~5k) and the device
    table is states x vocab x 4 bytes — keep depth <= 2 on 32k-vocab
    tokenizers."""
    ws = r"[ \n\t]*"
    value = _JSON_SCALAR
    for _ in range(max_depth):
        obj = (f"\\{{{ws}({_JSON_STRING}{ws}:{ws}{value}"
               f"({ws},{ws}{_JSON_STRING}{ws}:{ws}{value})*)?{ws}\\}}")
        arr = f"\\[{ws}({value}({ws},{ws}{value})*)?{ws}\\]"
        value = f"({_JSON_SCALAR}|{obj}|{arr})"
    return (f"\\{{{ws}({_JSON_STRING}{ws}:{ws}{value}"
            f"({ws},{ws}{_JSON_STRING}{ws}:{ws}{value})*)?{ws}\\}}")


# ---------------------------------------------------------------------------
# JSON Schema -> regex (structured output beyond bare json_object mode)
# ---------------------------------------------------------------------------

_RE_SPECIAL = frozenset(b"\\()[]{}*+?|.^$-")
_WS = r"[ \n\t]*"
_JSON_INTEGER = r"-?(0|[1-9][0-9]*)"
# one JSON-text string "character": a plain char or an escape sequence
_JSON_CHAR = r'([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})'


def _re_escape(text: str) -> str:
    """Literal text -> this module's regex dialect (byte-wise; regex
    metacharacters backslash-escaped, non-printable bytes as \\xNN)."""
    out = []
    for b in text.encode("utf-8"):
        if b in _RE_SPECIAL:
            out.append("\\" + chr(b))
        elif 0x20 <= b < 0x7F:
            out.append(chr(b))
        else:
            out.append(f"\\x{b:02X}")
    return "".join(out)


def _json_literal(value) -> str:
    """A python value -> regex matching exactly its canonical JSON
    spelling (ensure_ascii keeps the bytes printable)."""
    import json as _json
    return _re_escape(_json.dumps(value, separators=(",", ":")))


def json_schema_regex(schema: dict, *, max_depth: int = 4,
                      max_optional: int = 6) -> str:
    """Compile a practical JSON-Schema subset into a regex for the
    byte-DFA -> token-table pipeline (the same machinery json_object
    mode uses; exact through sampling and speculation).

    Supported: `type` object / array / string / integer / number /
    boolean / null (or a list of those), `properties` + `required`,
    `enum` / `const` (any JSON values), `anyOf` / `oneOf`, `items`,
    `minItems` / `maxItems`, `minLength` / `maxLength`. Semantics are
    generation-oriented (OpenAI structured-output conventions):

      * objects are CLOSED (no additional properties) and their keys
        appear in declared order; optional keys (absent from
        `required`) may be omitted — at most `max_optional` optional
        keys per object (the ordering regex doubles per optional key);
      * nesting is bounded by `max_depth` (depth-k JSON is regular;
        unbounded nesting is not);
      * arrays without `items`, and bare {} subschemas, accept any
        scalar;
      * numeric ranges (`minimum` / `maximum`), string `pattern`, and
        `additionalProperties` are rejected loudly rather than
        silently ignored.

    Raises ValueError on anything outside the subset."""
    if not isinstance(schema, dict):
        raise ValueError("schema must be a JSON object")
    return _schema_re(schema, max_depth, max_optional)


_UNSUPPORTED = ("minimum", "maximum", "exclusiveMinimum",
                "exclusiveMaximum", "multipleOf", "pattern",
                "additionalProperties", "patternProperties", "allOf",
                "not", "$ref", "uniqueItems", "minProperties",
                "maxProperties")

# optional-key ordering doubles the regex per optional key and nesting
# multiplies levels together, so a small schema can compound into a
# multi-GB pattern. Checked at EVERY recursion return (bottom-up), so
# an inner level trips the cap before an outer level multiplies it —
# peak memory stays ~branching x cap, never the full product.
MAX_SCHEMA_REGEX = 1 << 20  # 1 MB of pattern is already a huge DFA


def _schema_re(s, depth: int, max_opt: int) -> str:
    out = _schema_re_inner(s, depth, max_opt)
    if len(out) > MAX_SCHEMA_REGEX:
        raise ValueError(
            "json_schema: schema compiles to a regex over "
            f"{MAX_SCHEMA_REGEX >> 20} MB (optional-key combinations "
            "double per optional key and compound across nesting); "
            "mark more keys required or flatten the schema")
    return out


def _schema_re_inner(s, depth: int, max_opt: int) -> str:
    if not isinstance(s, dict):
        raise ValueError(f"subschema must be an object, got {type(s)}")
    for key in _UNSUPPORTED:
        if key in s:
            raise ValueError(
                f"json_schema: {key!r} is not supported (the regex/DFA "
                "pipeline cannot express it); remove it or use a "
                "supported equivalent")
    if "const" in s:
        return _json_literal(s["const"])
    if "enum" in s:
        if not s["enum"]:
            raise ValueError("json_schema: empty enum matches nothing")
        return "(" + "|".join(_json_literal(v) for v in s["enum"]) + ")"
    for comb in ("anyOf", "oneOf"):
        if comb in s:
            branches = s[comb]
            if not isinstance(branches, list) or not branches:
                raise ValueError(f"json_schema: {comb} needs a non-empty "
                                 "list")
            return ("(" + "|".join(_schema_re(b, depth, max_opt)
                                   for b in branches) + ")")
    t = s.get("type")
    if isinstance(t, list):
        if not t:
            raise ValueError("json_schema: empty type list")
        return ("(" + "|".join(_schema_re({**s, "type": one}, depth,
                                          max_opt)
                               for one in t) + ")")
    if t == "object" or (t is None and "properties" in s):
        return _object_re(s, depth, max_opt)
    if t == "array" or (t is None and "items" in s):
        return _array_re(s, depth, max_opt)
    if t == "string":
        lo = int(s.get("minLength", 0))
        hi = s.get("maxLength")
        if lo == 0 and hi is None:
            return _JSON_STRING
        _check_bound(lo, hi, "minLength/maxLength")
        reps = (f"{{{lo},}}" if hi is None else f"{{{lo},{int(hi)}}}")
        return f'"{_JSON_CHAR}{reps}"'
    if t == "integer":
        return _JSON_INTEGER
    if t == "number":
        return _JSON_NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t is None:
        return _JSON_SCALAR  # unconstrained subschema: any scalar
    raise ValueError(f"json_schema: unsupported type {t!r}")


def _object_re(s, depth: int, max_opt: int) -> str:
    if depth <= 0:
        raise ValueError("json_schema: nesting exceeds max_depth")
    props = s.get("properties", {})
    if not isinstance(props, dict):
        raise ValueError('json_schema: "properties" must be an object')
    required = s.get("required", [])
    unknown = set(required) - set(props)
    if unknown:
        raise ValueError(f"json_schema: required keys {sorted(unknown)} "
                         "missing from properties")
    req = set(required)
    n_opt = sum(1 for k in props if k not in req)
    if n_opt > max_opt:
        raise ValueError(
            f"json_schema: {n_opt} optional properties > max_optional="
            f"{max_opt} (the ordering regex doubles per optional key); "
            "mark more keys required or raise max_optional")
    keys = list(props)
    items = [f"{_json_literal(k)}{_WS}:{_WS}"
             f"{_schema_re(props[k], depth - 1, max_opt)}"
             for k in keys]

    # properties in declared order, commas between those present;
    # memoized over (index, anything-emitted-yet) so the string grows
    # ~2x per OPTIONAL key only
    memo: dict[tuple[int, bool], str] = {}

    def tail(i: int, seen: bool) -> str:
        if i == len(keys):
            return ""
        got = memo.get((i, seen))
        if got is not None:
            return got
        sep = f"{_WS},{_WS}" if seen else ""
        with_it = f"{sep}{items[i]}{tail(i + 1, True)}"
        if keys[i] in req:
            out = with_it
        else:
            out = f"({with_it}|{tail(i + 1, seen)})"
        memo[(i, seen)] = out
        return out

    return f"\\{{{_WS}{tail(0, False)}{_WS}\\}}"


def _check_bound(lo: int, hi, what: str) -> None:
    """Schema-level bound validation: the regex engine caps {m,n}
    repeats at 256, so an oversize bound must fail HERE with the
    keyword named — not later as an opaque regex-internal error."""
    if lo < 0 or (hi is not None and int(hi) < lo):
        raise ValueError(f"json_schema: bad {what}")
    if lo > 256 or (hi is not None and int(hi) > 256):
        raise ValueError(
            f"json_schema: {what} above 256 is not supported (the "
            "DFA pipeline caps bounded repeats at 256); drop the bound "
            "or lower it")


def _array_re(s, depth: int, max_opt: int) -> str:
    if depth <= 0:
        raise ValueError("json_schema: nesting exceeds max_depth")
    item = _schema_re(s.get("items", {}), depth - 1, max_opt)
    lo = int(s.get("minItems", 0))
    hi = s.get("maxItems")
    _check_bound(lo, hi, "minItems/maxItems")
    more = f"{_WS},{_WS}{item}"
    if hi is None:
        body = (f"({item}({more})*)?" if lo == 0
                else f"{item}({more}){{{lo - 1},}}")
    elif int(hi) == 0:
        body = ""
    elif lo == 0:
        body = f"({item}({more}){{0,{int(hi) - 1}}})?"
    else:
        body = f"{item}({more}){{{lo - 1},{int(hi) - 1}}}"
    return f"\\[{_WS}{body}{_WS}\\]"

"""Host-side page allocator: refcounts, prefix sharing, LRU reuse.

Pure-Python bookkeeping for the device page pool
(`paged_engine.PagedKVCache`). The device never allocates — the
scheduler either reserves a request's whole chain at admission
(allocation="reserve") or grows chains just-in-time before each decode
dispatch, preempting the youngest slot on exhaustion
(allocation="ondemand" — see paged_server). Either way every write the
device issues lands in a page the host put in the table first.

Sharing model (radix-style, page granularity): a FULL page of kv is
identified by the token chain that produced it — the cache key is
(parent_chain_hash, page_tokens), where parent_chain_hash is a running
hash over every preceding page's key (vLLM-style block hashing). Keys
are pure CONTENT: they never reference physical page ids, so reusing an
evicted page's id can never alias an old chain (the ABA hazard of
id-based keys). Walking a prompt page-by-page either extends a chain of
hits
(each hit bumps a refcount and costs zero prefill FLOPs) or misses and
switches to fresh private pages. On release, a request's full private
pages are KEYED into the cache (refcount 0, LRU-ordered) rather than
freed — a later request with the same token prefix (same system prompt,
same few-shot header, a multi-turn follow-up replaying the conversation)
reuses them, generated tokens included. The free list refills by evicting
least-recently-used refcount-0 cached pages on demand.

Page lifecycle:

    free --alloc--> active-private --release(full)--> cached
      ^                 |release(partial)               |   ^
      |                 v                        lookup |   | release
      +--evict-- cached <----- active-shared <----------+---+

A page is EVICTABLE iff refcount 0; keyed pages stay discoverable while
actively shared, so any number of in-flight slots can share one page.

Immutability invariant (what makes sharing safe): keyed pages are always
FULL pages strictly before every sharing slot's first private position,
and the engine only writes at positions >= lengths >= that boundary. An
evicted page has refcount 0 — no slot's table points at it.

Eviction orphans: evicting a parent page leaves cached children
unreachable for now (lookup walks front-to-back and stops at the first
miss — attention needs contiguous prefix KV). They age out via LRU, or
become reachable again if another request re-materializes the same
parent content (keys are content-only, so the chain re-links).
Correctness is unaffected either way — a miss is just a miss.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib

from cloud_server_tpu.inference.cache_telemetry import CacheTelemetry

# Root digest for every chain. Chain hashing uses blake2b-128 over
# (parent_digest, page_tokens) rather than Python's builtin hash():
# the builtin's int-tuple hash is 64-bit, non-cryptographic, and
# deterministic across processes — an attacker who can choose token ids
# could construct two prompt chains whose keys collide and read another
# request's cached KV (the exact design vLLM patched in
# CVE-2025-25183). The token tuple itself also rides in the key, so a
# wrong hit additionally requires identical page content.
_ROOT = b"\x00" * 16


def _chain_digest(parent: bytes, page_tokens: tuple[int, ...]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(",".join(map(str, page_tokens)).encode())
    return h.digest()


def _root_for(namespace: str) -> bytes:
    """Chain root for a KV namespace. Different adapters produce
    DIFFERENT kv for identical tokens, so their chains must never
    collide — the namespace (adapter name; "" = base model) salts the
    root digest, partitioning the cache."""
    if not namespace:
        return _ROOT
    h = hashlib.blake2b(_ROOT, digest_size=16)
    h.update(b"ns:" + namespace.encode())
    return h.digest()


@dataclasses.dataclass
class AllocatorStats:
    """Point-in-time allocator snapshot. Occupancy fields partition
    the pool (`pages_total == pages_free + pages_cached +
    pages_active`); the rest are LIFETIME counters. `prefix_hit_pages`
    counts every page served from the cache across all walks;
    `prefix_miss_pages` counts one page per walk that BROKE at a miss
    (the walk stops at the first miss, so un-walked pages are not
    misses here — per-tenant miss accounting in
    `cache_telemetry.CacheTelemetry` counts the full un-shared
    remainder instead). `hits_tokens` is the token value of the hit
    pages (hit pages x page_size — prefill work the cache absorbed);
    `namespaces` counts the distinct KV namespaces (base model +
    per-request LoRA adapters) that ever touched the cache."""

    pages_total: int
    pages_free: int
    pages_cached: int   # refcount-0 keyed pages (evictable)
    pages_active: int   # referenced by >= 1 slot
    prefix_hit_pages: int = 0
    prefix_miss_pages: int = 0
    evictions: int = 0
    hits_tokens: int = 0
    namespaces: int = 0


class BlockAllocator:
    """Allocator for a pool of `num_pages` device pages of `page_size`
    tokens. Not thread-safe — callers hold the scheduler lock."""

    def __init__(self, num_pages: int, page_size: int,
                 telemetry: CacheTelemetry | None = None):
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: collections.deque[int] = collections.deque(
            range(num_pages))
        self._ref = [0] * num_pages
        # key -> page for every keyed page (active or not); _evictable
        # holds ONLY refcount-0 keyed pages, in insertion order — python
        # dicts iterate oldest-first, giving an O(1) LRU (pages re-insert
        # on every release, so insertion order IS recency order)
        self._cache: dict[tuple[bytes, tuple[int, ...]], int] = {}
        self._key_of: dict[int, tuple[bytes, tuple[int, ...]]] = {}
        self._evictable: dict[int, None] = {}
        self.prefix_hit_pages = 0
        self.prefix_miss_pages = 0
        self.evictions = 0
        # lifetime flow counters (the flight recorder deltas these per
        # iteration): fresh pages handed out, pages whose refcount hit 0
        self.pages_allocated = 0
        self.pages_released = 0
        self._namespaces: set[str] = set()
        # per-page attribution sidecar state (plain fixed-size lists —
        # O(1) per event): the tenant whose alloc produced the page, and
        # for KEYED pages the chain position / digest / the iteration it
        # last became evictable (eviction forensics reads all four)
        self._owner: list[str | None] = [None] * num_pages
        self._depth = [0] * num_pages
        self._digest: list[bytes | None] = [None] * num_pages
        self._idle_since = [0] * num_pages
        # attribution / forensics / hot-prefix-sketch ledger
        # (inference/cache_telemetry.py): always present — the record
        # hooks are plain dict arithmetic — so library users get the
        # same observability the paged server surfaces
        self.telemetry = (telemetry if telemetry is not None
                          else CacheTelemetry(page_size))

    # -- capacity -----------------------------------------------------------

    @property
    def available(self) -> int:
        """Pages obtainable right now (free + evictable cached)."""
        return len(self._free) + len(self._evictable)

    def stats(self) -> AllocatorStats:
        active = self.num_pages - len(self._free) - len(self._evictable)
        return AllocatorStats(
            pages_total=self.num_pages, pages_free=len(self._free),
            pages_cached=len(self._evictable), pages_active=active,
            prefix_hit_pages=self.prefix_hit_pages,
            prefix_miss_pages=self.prefix_miss_pages,
            evictions=self.evictions,
            hits_tokens=self.prefix_hit_pages * self.page_size,
            namespaces=len(self._namespaces))

    # -- allocate / share ---------------------------------------------------

    def _evict_one(self, forcer: str | None = None) -> None:
        """Reclaim the LRU refcount-0 keyed page. `forcer` is the
        tenant whose alloc drained the free list — eviction forensics
        pairs it with the page's producing tenant (who suffered)."""
        page = next(iter(self._evictable))  # oldest refcount-0 page
        del self._evictable[page]
        del self._cache[self._key_of.pop(page)]
        self._free.append(page)
        self.evictions += 1
        self.telemetry.record_evict(
            self._owner[page], forcer,
            self.telemetry.iteration - self._idle_since[page],
            self._depth[page], self._digest[page])
        self._owner[page] = None
        self._digest[page] = None
        self._depth[page] = 0

    def alloc(self, n: int,
              tenant: str | None = None) -> list[int] | None:
        """n fresh private pages (refcount 1), evicting cached pages as
        needed; None (and no side effects) if capacity is short.
        `tenant` attributes the pages (and any evictions this alloc
        forces) for the cache-telemetry ledger."""
        if self.available < n:
            return None
        while len(self._free) < n:
            self._evict_one(forcer=tenant)
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
            self._owner[p] = tenant
        self.pages_allocated += n
        if n:
            self.telemetry.record_alloc(tenant, n)
        return pages

    def lookup_prefix(self, prompt: list[int], namespace: str = "",
                      tenant: str | None = None) -> tuple[list[int], int]:
        """Walk the prompt's full pages through the prefix cache.

        Returns (shared_pages, shared_len_tokens). Each hit page's
        refcount is bumped — the caller owns one reference per returned
        page and must release() them. At least one prompt token is always
        left un-shared so admission has a position to produce first-token
        logits from. `namespace` partitions chains whose KV differs for
        identical tokens (per-request LoRA adapters); `tenant`
        attributes the walk's hits/misses (and the hot-prefix-sketch
        update) to the requesting tenant's ledger.
        """
        ps = self.page_size
        self._namespaces.add(namespace)
        shared: list[int] = []
        parent = _root_for(namespace)
        limit = (len(prompt) - 1) // ps  # full pages, leaving >= 1 token
        for i in range(limit):
            key = (parent, tuple(prompt[i * ps:(i + 1) * ps]))
            page = self._cache.get(key)
            if page is None:
                self.prefix_miss_pages += 1
                break
            self.prefix_hit_pages += 1
            self._ref[page] += 1
            self._evictable.pop(page, None)  # active again
            shared.append(page)
            parent = _chain_digest(*key)
        hits = len(shared)
        self.telemetry.record_walk(
            tenant, hits, limit - hits, len(prompt) - hits * ps,
            parent if hits else None)
        if hits:
            self.telemetry.record_alloc(tenant, hits)  # refs held
        return shared, hits * ps

    def import_chain(self, tokens: list[int], namespace: str = "",
                     tenant: str | None = None) -> list[tuple[int, int]]:
        """Key a migrated chain's full pages into the cache so a
        continuation admission re-hits them.

        Walks the chain keys for every full page of `tokens` (the
        migration snapshot's committed stream). A key already cached
        DEDUPES — the destination holds identical content, nothing to
        transfer for that position. A miss claims a page (evicting LRU
        cached pages like alloc) and keys it; the caller must scatter
        the snapshot's KV into every returned page BEFORE any lookup
        can hit it (the scheduler holds its step lock across
        import + scatter, and admissions only run inside the step).

        Returns [(chain_index, page_id)] for the pages this call
        created — the positions whose device KV the caller must fill.
        Capacity shortage stops the walk early: a partial import is a
        valid (shorter) cached prefix, just a smaller prefill saving.
        Created pages land at refcount 0, cached and evictable —
        exactly the state a released chain leaves behind.
        """
        ps = self.page_size
        self._namespaces.add(namespace)
        parent = _root_for(namespace)
        fill: list[tuple[int, int]] = []
        created: list[int] = []
        for i in range(len(tokens) // ps):
            key = (parent, tuple(tokens[i * ps:(i + 1) * ps]))
            page = self._cache.get(key)
            if page is not None:
                parent = _chain_digest(*key)
                continue
            if self.available < 1:
                break
            if not self._free:
                self._evict_one(forcer=tenant)
            page = self._free.popleft()
            # refcount 1 for the duration of the walk: eviction only
            # touches refcount-0 pages, so later iterations of THIS
            # import can never reclaim an earlier created page
            self._ref[page] = 1
            self._owner[page] = tenant
            self.pages_allocated += 1
            self._cache[key] = page
            self._key_of[page] = key
            parent = _chain_digest(*key)
            self._depth[page] = i + 1
            self._digest[page] = parent
            created.append(page)
            fill.append((i, page))
        if created:
            self.telemetry.record_alloc(tenant, len(created))
            for page in created:
                self._ref[page] = 0
                self.pages_released += 1
                self._evictable[page] = None
                self._idle_since[page] = self.telemetry.iteration
            self.telemetry.record_release(tenant, len(created))
        return fill

    # -- release ------------------------------------------------------------

    def release(self, pages: list[int], tokens: list[int],
                namespace: str = "",
                tenant: str | None = None) -> None:
        """Drop one reference per chain page. Pages reaching refcount 0
        become cached (if they are full pages covered by `tokens` — the
        slot's committed prompt + generated ids) or return to the free
        list (the partial tail). `namespace` must match the lookup's;
        `tenant` the lookup/alloc's (the ledger drops the refs it
        counted there)."""
        ps = self.page_size
        self._namespaces.add(namespace)
        parent = _root_for(namespace)
        for i, page in enumerate(pages):
            self._ref[page] -= 1
            full = (i + 1) * ps <= len(tokens)
            if full:
                key = (parent, tuple(tokens[i * ps:(i + 1) * ps]))
                if page not in self._key_of and key not in self._cache:
                    # (a duplicate-content page under another id stays
                    # unkeyed; it frees below when unreferenced)
                    self._cache[key] = page
                    self._key_of[page] = key
                # content digest: the chain continues regardless of which
                # physical page is canonical for this position
                parent = _chain_digest(*key)
                if page in self._key_of:
                    # forensics sidecar for the KEYED page: chain
                    # position + digest (stamped once — the digest is a
                    # constant of the content) so an eviction needs no
                    # re-hash
                    self._depth[page] = i + 1
                    self._digest[page] = parent
            if self._ref[page] <= 0:
                self._ref[page] = 0
                self.pages_released += 1
                if page in self._key_of:
                    self._evictable[page] = None
                    # LRU idle clock: age-at-eviction counts from the
                    # moment the page LAST became evictable
                    self._idle_since[page] = self.telemetry.iteration
                else:
                    self._free.append(page)
                    self._owner[page] = None
        if pages:
            self.telemetry.record_release(tenant, len(pages))

"""KV-cache & memory observability: per-tenant prefix-cache
attribution, eviction forensics, and the bounded hot-prefix sketch.

The paged KV pool and its radix prefix cache
(`inference/block_allocator.py`) are the serving stack's scarcest
resource, and until this module they were nearly blind: the allocator
kept flat lifetime counters with no notion of WHO hit, who missed, or
whose churn evicted whose system prompt. This module is the
measurement layer ROADMAP item 3 (fleet-scale prefix cache) scores its
policies against:

  * **Per-tenant attribution** (`record_walk` / `record_alloc` /
    `record_release` / `record_saved`): the allocator calls in at the
    host moments it already owns — one call per prefix walk, one per
    alloc/release — so every tenant accumulates pages held, prefix
    pages/tokens hit and missed, and realized saved tokens.
    `hit_tokens` counts at LOOKUP time (optimistic — a page-famine
    retry next step walks and counts again); `saved_tokens` is
    recorded by the scheduler only once the admission actually
    succeeded, so the two diverge exactly when lookups were wasted.
  * **Eviction forensics** (`record_evict`): when the allocator's
    `_evict_one` reclaims a keyed page it reports the VICTIM (the
    tenant whose request produced the page) and the FORCER (the tenant
    whose `alloc` drained the free list) — per-tenant
    suffered/caused counters, a bounded victim×forcer matrix, and a
    ring of recent evictions (chain digest, depth, idle age) that
    answers "whose churn evicted whose system prompt" post-mortem.
  * **Hot-prefix sketch**: a bounded counter table over chain digests
    (the deepest hit node per walk). The hot path pays one dict
    update; top-K selection and the occasional compaction (drop the
    cold half when the table overflows `capacity`) are amortized /
    read-path work. `top_prefixes()` is the artifact item 3(a)'s
    prefix-aware router `_pick` will score candidate replicas with,
    and `merge_top_prefixes` / `merge_cache_stats` are the fleet
    merge: counts sum per digest, hit-rate ratios recompute from the
    merged totals (the `tenant_fair_share` rule — ratios never add).

Concurrency: mutators run on the scheduler thread (under the server's
locks); readers run on the scrape thread. The internal `_lock` guards
only plain dict/deque arithmetic, so contention is negligible — the
same discipline as `qos.TenantRegistry`. `iteration` is a plain int
the scheduler stamps once per step (GIL-atomic write, racy-by-design
monitoring read: a stale value skews a sketch recency tag by one
iteration at most).

Stdlib-only and jax-free by contract: this module rides the analysis
hot-path lint roster AND the DD3 host-policy roster
(`cloud_server_tpu/analysis/`), so device work, numpy buffers, blocking
syncs, wall-clock reads, and host I/O can never creep into the
record path.
"""

from __future__ import annotations

import collections
import threading

# Matches qos.DEFAULT_TENANT (not imported: qos pulls the server import
# chain, and the two constants are pinned equal by a test instead).
DEFAULT_TENANT = "default"

# Sketch bounds: TOP_K is the export size, CAPACITY the tracked-chain
# bound. When the table crosses CAPACITY it compacts to the hottest
# CAPACITY // 2 entries — a space-saving-style bounded counter, so a
# long-tail chain can undercount but a genuinely hot chain cannot be
# displaced by one-hit wonders.
SKETCH_TOP_K = 32
SKETCH_CAPACITY = 512
FORENSICS_RING = 256

# Fixed histogram ladders (identical on every replica, so fleet merges
# are exact bucket-for-bucket — the serving_metrics rule).
CHAIN_DEPTH_BUCKETS: tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256)
PAGE_AGE_BUCKETS: tuple[float, ...] = (
    1, 4, 16, 64, 256, 1024, 4096, 16384, 65536)
EVICTABLE_FRAC_BUCKETS: tuple[float, ...] = (
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)

# Histogram families (name, help) — registered eagerly by the paged
# server (`register_cache_hists`) so the docs drift check sees them
# before any traffic.
CHAIN_DEPTH_HIST = (
    "cache_chain_depth_pages",
    "Prefix-cache pages hit per admission walk (0 = cold miss)")
PAGE_AGE_HIST = (
    "cache_page_age_at_eviction_iters",
    "Scheduler iterations a page sat evictable before being reclaimed "
    "(near-zero = the cache is thrashing)")
EVICTABLE_FRAC_HIST = (
    "pool_evictable_frac",
    "Per-busy-iteration reclaimable pool fraction "
    "((free + cached) / total) — the HBM-pressure watermark")


def register_cache_hists(registry) -> dict:
    """Eager registration of the cache/memory histogram families in a
    `utils.serving_metrics.MetricsRegistry`; returns {short_key: hist}
    for the observe paths (a dict lookup per observation, never a
    registry get-or-create)."""
    return {
        "chain_depth": registry.histogram(
            *CHAIN_DEPTH_HIST, buckets=CHAIN_DEPTH_BUCKETS),
        "page_age": registry.histogram(
            *PAGE_AGE_HIST, buckets=PAGE_AGE_BUCKETS),
        "evictable_frac": registry.histogram(
            *EVICTABLE_FRAC_HIST, buckets=EVICTABLE_FRAC_BUCKETS),
    }


class _TenantCacheStats:
    """Per-tenant cache ledger (telemetry-private)."""

    __slots__ = ("lookups", "hit_pages", "miss_pages", "hit_tokens",
                 "miss_tokens", "saved_tokens", "pages_held",
                 "evicted_pages", "evictions_caused")

    def __init__(self):
        self.lookups = 0
        self.hit_pages = 0
        self.miss_pages = 0
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.saved_tokens = 0
        self.pages_held = 0
        self.evicted_pages = 0
        self.evictions_caused = 0

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class CacheTelemetry:
    """The allocator's attribution + forensics + sketch sidecar.

    One instance per `BlockAllocator` (constructed by it when the
    caller passes none). The allocator calls the record_* hooks at the
    walk/alloc/release/evict moments it already owns; the paged server
    stamps `iteration` once per step and attaches the registry
    histograms (`attach_hists`) so depth/age observations land in
    mergeable fixed-ladder families. Everything is plain host
    arithmetic — zero dispatches, zero syncs (hot-path lint + the
    dispatch-count regression clone enforce this).
    """

    def __init__(self, page_size: int, *, top_k: int = SKETCH_TOP_K,
                 capacity: int = SKETCH_CAPACITY,
                 ring: int = FORENSICS_RING):
        if top_k <= 0 or capacity < 2 * top_k:
            raise ValueError(
                f"sketch needs top_k > 0 and capacity >= 2 * top_k "
                f"(got {top_k=}, {capacity=})")
        self.page_size = page_size
        self.top_k = top_k
        self.capacity = capacity
        # scheduler-stamped flight-recorder iteration index (plain int:
        # GIL-atomic write on the scheduler thread, monitoring reads
        # may lag by one iteration — recency tags only)
        self.iteration = 0
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantCacheStats] = {}
        # chain digest -> [hits, depth_pages, last_hit_iteration]
        self._chains: dict[bytes, list] = {}
        self._evictions = collections.deque(maxlen=ring)
        self._evict_matrix: dict[tuple[str, str], int] = {}
        self.hists: dict = {}  # attach_hists; empty = skip observes

    def attach_hists(self, hists: dict) -> None:
        """Wire the registry histograms (`register_cache_hists`) into
        the observe paths; without them observations are skipped
        (library/standalone allocator use)."""
        self.hists = dict(hists)

    def _tenant(self, tenant: str | None) -> _TenantCacheStats:
        """Ledger for a RESOLVED tenant name (callers pass names the
        QoS registry already collapsed; None — no QoS — lands on the
        default ledger, mirroring `qos.resolve`). Caller holds _lock."""
        name = tenant or DEFAULT_TENANT
        st = self._tenants.get(name)
        if st is None:
            st = self._tenants[name] = _TenantCacheStats()
        return st

    # -- record hooks (allocator/scheduler hot path) ------------------------

    def record_walk(self, tenant: str | None, hit_pages: int,
                    miss_pages: int, prefilled_tokens: int,
                    chain_digest: bytes | None) -> None:
        """One prefix walk: `hit_pages` served from cache, `miss_pages`
        full prompt pages that will be freshly written,
        `prefilled_tokens` the un-shared prompt remainder (tail
        included). `chain_digest` names the deepest hit node (None on
        a cold miss) and feeds the hot-prefix sketch."""
        ps = self.page_size
        with self._lock:
            st = self._tenant(tenant)
            st.lookups += 1
            st.hit_pages += hit_pages
            st.hit_tokens += hit_pages * ps
            st.miss_pages += miss_pages
            st.miss_tokens += prefilled_tokens
            if chain_digest is not None:
                entry = self._chains.get(chain_digest)
                if entry is None:
                    self._chains[chain_digest] = [
                        1, hit_pages, self.iteration]
                    if len(self._chains) > self.capacity:
                        self._compact()
                else:
                    entry[0] += 1
                    # the digest names the whole chain, so depth is a
                    # constant of the key; keep the max for safety
                    if hit_pages > entry[1]:
                        entry[1] = hit_pages
                    entry[2] = self.iteration
        h = self.hists.get("chain_depth")
        if h is not None:
            h.observe(hit_pages)

    def _compact(self) -> None:
        """Drop the cold half once the chain table overflows (caller
        holds _lock). Amortized: runs once per capacity/2 NEW chains."""
        keep = sorted(self._chains.items(),
                      key=lambda kv: (kv[1][0], kv[1][2]),
                      reverse=True)[:self.capacity // 2]
        self._chains = dict(keep)

    def record_alloc(self, tenant: str | None, n: int) -> None:
        with self._lock:
            self._tenant(tenant).pages_held += n

    def record_release(self, tenant: str | None, n: int) -> None:
        with self._lock:
            st = self._tenant(tenant)
            st.pages_held = max(0, st.pages_held - n)

    def record_saved(self, tenant: str | None, tokens: int) -> None:
        """Realized prefill savings: called by the scheduler once an
        admission SUCCEEDED with `tokens` of its prompt served from
        cache (lookup-time hit_tokens counts optimistically; this one
        only counts wins that turned into skipped prefill work)."""
        with self._lock:
            self._tenant(tenant).saved_tokens += tokens

    def record_evict(self, victim: str | None, forcer: str | None,
                     age_iterations: int, depth: int,
                     chain_digest: bytes | None) -> None:
        """One keyed-page eviction: `victim` produced the page,
        `forcer`'s alloc reclaimed it, `age_iterations` is how long it
        sat evictable, `depth` its position in its chain."""
        vic = victim or DEFAULT_TENANT
        frc = forcer or DEFAULT_TENANT
        with self._lock:
            self._tenant(vic).evicted_pages += 1
            self._tenant(frc).evictions_caused += 1
            key = (vic, frc)
            self._evict_matrix[key] = self._evict_matrix.get(key, 0) + 1
            self._evictions.append({
                "iteration": self.iteration,
                "victim": vic,
                "forcer": frc,
                "age_iterations": age_iterations,
                "depth": depth,
                "key": (chain_digest.hex()
                        if chain_digest is not None else None),
            })
        h = self.hists.get("page_age")
        if h is not None:
            h.observe(age_iterations)

    # -- scrape-path views --------------------------------------------------

    def tenant_stats(self) -> dict[str, dict]:
        """{tenant: ledger dict} — counts only; ratios are the
        consumer's job (so fleet merges stay exact)."""
        with self._lock:
            return {name: st.as_dict()
                    for name, st in self._tenants.items()}

    def top_prefixes(self, k: int | None = None) -> list[dict]:
        """The hottest `k` (default top_k) prefix chains, hottest
        first: {"key": digest hex, "depth": pages, "hits": count,
        "last_hit_iteration": flight index}."""
        k = self.top_k if k is None else k
        with self._lock:
            items = sorted(self._chains.items(),
                           key=lambda kv: (kv[1][0], kv[1][2]),
                           reverse=True)[:max(k, 0)]
        return [{"key": dig.hex(), "depth": e[1], "hits": e[0],
                 "last_hit_iteration": e[2]} for dig, e in items]

    def recent_evictions(self, n: int | None = None) -> list[dict]:
        """The last `n` (default: full ring) eviction-forensics
        records, oldest first."""
        with self._lock:
            out = list(self._evictions)
        return out if n is None else out[-max(n, 0):]

    def eviction_matrix(self) -> dict[str, dict[str, int]]:
        """{victim: {forcer: pages}} — who evicted whom, lifetime."""
        with self._lock:
            items = list(self._evict_matrix.items())
        out: dict[str, dict[str, int]] = {}
        for (vic, frc), n in items:
            out.setdefault(vic, {})[frc] = n
        return out


# ---------------------------------------------------------------------------
# fleet merge (ReplicatedRouter.cache_stats)
# ---------------------------------------------------------------------------


def merge_top_prefixes(sketches, k: int = SKETCH_TOP_K) -> list[dict]:
    """Merge per-replica `top_prefixes` exports into the fleet top-K:
    hits SUM per chain digest (the same prompt hot on two replicas is
    twice as hot fleet-wide), depth is a constant of the digest (max
    kept for safety), recency is the max last-hit index. Exact for
    every chain that made each replica's export."""
    merged: dict[str, dict] = {}
    for sketch in sketches:
        for e in sketch:
            cur = merged.get(e["key"])
            if cur is None:
                merged[e["key"]] = dict(e)
            else:
                cur["hits"] += e["hits"]
                cur["depth"] = max(cur["depth"], e["depth"])
                cur["last_hit_iteration"] = max(
                    cur["last_hit_iteration"], e["last_hit_iteration"])
    return sorted(merged.values(),
                  key=lambda e: (e["hits"], e["last_hit_iteration"]),
                  reverse=True)[:max(k, 0)]


def hit_rate(hit_pages: int, miss_pages: int) -> float:
    """THE hit-rate definition (hit / walked full pages) — single
    server and fleet merge both call this, so the two views can never
    diverge (the `compute_fair_shares` pattern)."""
    total = hit_pages + miss_pages
    return hit_pages / total if total else 0.0


def merge_cache_stats(stats: list[dict],
                      k: int = SKETCH_TOP_K) -> dict:
    """Merge per-replica `cache_stats()` payloads into the fleet view:
    pool/prefix/tenant COUNTS sum, `hit_rate` recomputes from the
    merged totals (never added — two 0.5-hit-rate replicas read 0.5),
    sketches merge via `merge_top_prefixes`, forensics rings
    concatenate with a replica tag, matrices add cellwise. Returns {}
    for an empty fleet."""
    stats = [s for s in stats if s]
    if not stats:
        return {}
    pool: dict[str, float] = {}
    prefix: dict[str, float] = {}
    tenants: dict[str, dict] = {}
    matrix: dict[str, dict[str, int]] = {}
    evictions: list[dict] = []
    namespaces = 0
    for i, s in enumerate(stats):
        for f, v in s.get("pool", {}).items():
            pool[f] = pool.get(f, 0) + v
        for f, v in s.get("prefix", {}).items():
            if f != "hit_rate":
                prefix[f] = prefix.get(f, 0) + v
        namespaces = max(namespaces, s.get("namespaces", 0))
        for name, led in s.get("tenants", {}).items():
            cur = tenants.setdefault(name, dict.fromkeys(led, 0))
            for f, v in led.items():
                cur[f] = cur.get(f, 0) + v
        for vic, row in s.get("eviction_matrix", {}).items():
            out_row = matrix.setdefault(vic, {})
            for frc, n in row.items():
                out_row[frc] = out_row.get(frc, 0) + n
        evictions += [{"replica": i, **rec}
                      for rec in s.get("recent_evictions", [])]
    prefix["hit_rate"] = hit_rate(int(prefix.get("hit_pages", 0)),
                                  int(prefix.get("miss_pages", 0)))
    # derived fraction over the merged pool, not averaged fractions
    total = pool.get("pages_total", 0)
    pool["evictable_frac"] = (
        (pool.get("pages_free", 0) + pool.get("pages_cached", 0))
        / total if total else 0.0)
    return {
        "pool": pool,
        "prefix": prefix,
        "namespaces": namespaces,
        "tenants": tenants,
        "top_prefixes": merge_top_prefixes(
            [s.get("top_prefixes", []) for s in stats], k),
        "recent_evictions": evictions,
        "eviction_matrix": matrix,
        "per_replica": [
            {"replica": i,
             "pool": dict(s.get("pool", {})),
             "hit_rate": s.get("prefix", {}).get("hit_rate", 0.0)}
            for i, s in enumerate(stats)],
    }
